#!/usr/bin/env python
"""Open-loop load drill: sustained concurrent traffic against the serve stack.

Every other bench in the repo is *closed-loop* (the next request waits for
the last answer), which can never see queueing collapse. This drill is
**open-loop**: a seeded arrival schedule is generated up front (Poisson,
bursty, or ramp — arrivals never wait on completions), then threaded
clients fire a mixed scenario deck at :class:`serve.service.MSTService`:

* ``hit`` — repeats over a pre-solved pool (pure cache path),
* ``miss`` — distinct graphs across several shape buckets (solver path),
* ``batch`` — same-bucket bursts that must share lanes in the batch engine,
* ``dup`` — duplicate-digest storms (single-flight coalescing),
* ``update`` — incremental edge-update streams through ``serve/dynamic.py``
  (digest-chained, serialized per stream),
* ``oversize`` — bucket-ceiling bypasses to the single-graph path,

plus seeded **chaos faults armed mid-flight** (transient device failures,
a failed batch attempt) that the supervisor ladder must absorb: an
accepted query may degrade, it may never be *lost*.

Each request carries an ``slo_class`` tag; per-class goodput and
p50/p95/p99 latency are then **joined from the real ``serve.*`` /
``batch.*`` / ``compile.*`` bus events** by ``obs.slo`` (client-side
stopwatch accounting rides along as a cross-check). The report
(``ghs-load-report-v1``) embeds ``ghs-bench-metrics-v1`` gate metrics;
``tools/bench_gate.py`` compares them against the committed
``docs/BENCH_BASELINE_LOAD.json`` (the ``gate-load-v1`` workload) so p99
and goodput regressions fail CI the way weight parity does. See
``docs/LOAD_TESTING.md``.

**Fleet mode** (``--fleet N``): the same open-loop deck drives a
:class:`fleet.router.FleetRouter` over N worker subprocesses instead of the
in-process service — per-class latency then *includes* routing, framed-pipe
transport, per-worker queueing, and any failover re-queue, joined from the
router's ``fleet.request`` spans (with a per-worker SLO breakdown).
``--kill-worker [K]`` arms the fault registry inside worker K mid-window
(``fleet.worker.crash``: it dies in place of its next request, no response
flushed) and the drill then asserts the zero-lost-query contract: every
accepted query is answered (the crashed worker's in-flight requests
re-queue onto survivors), the dead worker restarts with backoff, rejoins
the ring, and serves a probe query. See ``docs/FLEET.md``.

**Disaster modes** (``--kill-router`` / ``--partition``, echo TCP
fleets over externally spawned ``--listen`` workers — the topology that
survives a router death): ``--kill-router`` crashes the router itself
mid-window with accepted work outstanding; a successor on the same
durable journal (``fleet/journal.py``) re-dials the still-live workers
(warm — their ``handled`` counts persist), replays the orphaned accepts,
and in-flight clients retry idempotently — ``lost_accepted == 0`` and
``journal_unanswered == 0`` gate EXACTLY (``gate-fleet-router-v1``,
``docs/BENCH_BASELINE_FLEET_ROUTER.json``). ``--partition K`` drives the
transport chaos layer: worker K's link goes one-way dark (frames
dropped, socket OPEN — detection must come from the lease, not EOF),
heals after ``--partition-duration``, and the drill asserts zero loss,
exactly one answer per query, and no lease trips on the healthy side.
See docs/LOAD_TESTING.md "Disaster drills".

**Elastic mode** (``--elastic``, needs ``--fleet``): an
:class:`fleet.autoscaler.Autoscaler` drives the pool during the window —
a zero-second wait budget makes the ramp deterministically provoke warm
scale-ups to ``--elastic-max``, and post-window idle drains the pool down
to ``--elastic-min`` (drain-aware retires: lowest-affinity victim, pinned
sessions migrating to ring inheritors). The drill waits for both
convergences, stops the autoscaler, and then — in ``--update-heavy`` mode
— publishes one more window per stream to prove the migrated streams
recover by snapshot+WAL replay with ZERO fresh solves. Scale event counts
gate EXACTLY (``gate-fleet-elastic-v1``,
``docs/BENCH_BASELINE_FLEET_ELASTIC.json``) and ``fleet.join.warm_s`` p95
gates as a wall-time ceiling; ``--kill-worker`` composes (the jax-free
``--test-echo`` kill-during-scale variant CI runs), asserting that a
crash landing mid-scale still loses nothing.

    python tools/load_drill.py --smoke --output load_report.json \
        --gate-baseline docs/BENCH_BASELINE_LOAD.json
    python tools/load_drill.py --smoke --update-baseline   # rewrite baseline
    python tools/load_drill.py --chaos --duration 20       # chaos scenario
    python tools/load_drill.py --smoke --no-chaos --fleet 3 --kill-worker 1 \
        --obs-dir fleet_obs --output fleet_kill.json       # kill drill

Exit code 0 iff every check passed (and the gate, when a baseline is given).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPORT_SCHEMA = "ghs-load-report-v1"
WORKLOAD = "gate-load-v1"
WORKLOAD_FLEET = "gate-fleet-v1"
WORKLOAD_FLEET_KILL = "gate-fleet-kill-v1"
WORKLOAD_FLEET_ELASTIC = "gate-fleet-elastic-v1"
WORKLOAD_FLEET_ELASTIC_KILL = "gate-fleet-elastic-kill-v1"
WORKLOAD_FLEET_ROUTER = "gate-fleet-router-v1"
WORKLOAD_FLEET_PARTITION = "gate-fleet-partition-v1"
WORKLOAD_OVERSIZE = "gate-oversize-v1"
WORKLOAD_VERIFY = "gate-verify-v1"
WORKLOAD_KINDS = "gate-analytics-v1"
WORKLOAD_STREAM = "gate-stream-v1"
WORKLOAD_STREAM_FLEET = "gate-stream-fleet-v1"
WORKLOAD_STREAM_KILL = "gate-stream-kill-v1"
WORKLOAD_STREAM_SHARDED = "gate-stream-sharded-drill-v1"
WORKLOAD_STREAM_SHARDED_KILL = "gate-stream-sharded-kill-v1"
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "BENCH_BASELINE_LOAD.json",
)
ANALYTICS_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs",
    "BENCH_BASELINE_ANALYTICS.json",
)

# Shape buckets the deck draws from (nodes, edges): hit/miss/batch classes
# stay inside the lane-admission ceiling; oversize deliberately exceeds it.
MISS_SHAPES = ((48, 120), (96, 280), (200, 620))
BATCH_SHAPE = (128, 400)
HIT_SHAPE = (64, 180)
UPDATE_SHAPE = (80, 240)
OVERSIZE_SHAPE = (70_000, 140_000)
STREAM_SHAPE = (128, 384)  # subscribed graphs (--update-heavy)
# --update-heavy --sharded-lane: oversize-by-node-bucket stream seeds —
# past the lane-engine admission ceiling (routes like a billion-edge
# graph), few enough edges to solve in drill time (tests/test_lane.py's
# oversize shape). Streams then run MESH-RESIDENT: windows scatter into
# the lane's donated slots and a kill is recovered by re-stage + replay.
STREAM_SHARDED_SHAPE = (70_000, 3_000)
STREAM_WINDOW_UPDATES = 6  # edge mutations per published window


def _stream_seed_shape(args):
    return STREAM_SHARDED_SHAPE if args.sharded_lane else STREAM_SHAPE


@dataclasses.dataclass
class Arrival:
    """One scheduled query: fire at ``at_s`` (relative to window start)."""

    at_s: float
    cls: str
    request: Optional[dict] = None  # None for update-stream arrivals
    stream: Optional[int] = None  # update-stream id (digest chained)
    updates: Optional[list] = None  # the update ops for a stream arrival


#: Set by main() from ``--wire binary``: solve requests then carry raw
#: u/v/w B-frame sections (Graph.to_wire) instead of the JSON edges list,
#: so the whole deck exercises the binary ingest + opaque-passthrough
#: plane end to end (same digests — the deck stays bit-reproducible).
_WIRE_BINARY = False


def _graph_request(g, cls: str) -> dict:
    if _WIRE_BINARY:
        return {"op": "solve", **g.to_wire(), "slo_class": cls}
    return {
        "op": "solve",
        "num_nodes": g.num_nodes,
        "edges": [[int(a), int(b), int(c)] for a, b, c in zip(g.u, g.v, g.w)],
        "slo_class": cls,
    }


# ----------------------------------------------------------------------
# Arrival models (open-loop: schedules are fixed before the first dispatch)
# ----------------------------------------------------------------------
def arrival_times(
    n: int, duration_s: float, model: str, rng: np.random.Generator
) -> np.ndarray:
    """``n`` seeded arrival offsets in ``[0, duration_s)``.

    ``poisson`` — exponential inter-arrival gaps, rescaled to the window
    (open-loop Poisson traffic at the target average rate).
    ``bursty`` — four ON windows separated by silence; arrivals uniform
    inside the ON windows (a thundering-herd shape).
    ``ramp`` — arrival density grows linearly across the window (the
    rate doubles by the end; models a traffic ramp-up).
    """
    if n <= 0:
        return np.empty(0)
    if model == "poisson":
        gaps = rng.exponential(1.0, size=n)
        t = np.cumsum(gaps)
        return t * (duration_s / t[-1])
    if model == "bursty":
        bursts = 4
        on = duration_s / (2 * bursts)
        starts = np.arange(bursts) * (2 * on)
        which = rng.integers(0, bursts, size=n)
        return starts[which] + rng.uniform(0, on, size=n)
    if model == "ramp":
        # Inverse-CDF of a linearly growing rate: t = D * sqrt(u).
        return duration_s * np.sqrt(rng.uniform(0, 1, size=n))
    raise ValueError(f"unknown arrival model {model!r}")


# ----------------------------------------------------------------------
# The scenario deck
# ----------------------------------------------------------------------
def build_deck(args, rng: np.random.Generator):
    """Returns ``(schedule, warm_graphs, stream_seeds, counts)``.

    ``warm_graphs`` are solved before the measured window (cache/bucket
    priming); ``stream_seeds`` seed the update sessions. Every graph is
    seeded from ``args.seed``, so the deck is bit-reproducible.
    """
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )

    D = args.duration
    scale = args.rate / 10.0  # --rate 10 is the smoke deck's reference size
    counts = {
        "hit": max(4, int(30 * scale)),
        "miss": max(3, int(24 * scale)),
        "batch": max(4, int(24 * scale)),
        "dup": max(4, int(12 * scale)),
        "update": max(3, int(15 * scale)),
        "oversize": args.oversize,
    }
    if args.oversize_heavy:
        # The bulk-vs-interactive scenario: enough oversize solves that one
        # is in flight for most of the window, with the interactive classes
        # arriving concurrently — the drill then checks interactive p99
        # stays bounded while bulk work runs (docs/SHARDED_LANE.md).
        counts["oversize"] = max(counts["oversize"], 4)
    schedule: List[Arrival] = []

    # hit: repeats over a small pre-solved pool.
    hit_pool = [
        gnm_random_graph(*HIT_SHAPE, seed=args.seed + 100 + i) for i in range(4)
    ]
    for i, t in enumerate(
        arrival_times(counts["hit"], D, args.arrival, rng)
    ):
        schedule.append(
            Arrival(float(t), "hit", _graph_request(hit_pool[i % 4], "hit"))
        )

    # miss: every query a distinct graph, cycling the shape buckets.
    for i, t in enumerate(
        arrival_times(counts["miss"], D, args.arrival, rng)
    ):
        shape = MISS_SHAPES[i % len(MISS_SHAPES)]
        g = gnm_random_graph(*shape, seed=args.seed + 1000 + i)
        schedule.append(Arrival(float(t), "miss", _graph_request(g, "miss")))

    # batch: same-bucket bursts — distinct digests arriving together so the
    # engine's forming queue actually builds multi-graph lanes.
    n_bursts = max(1, counts["batch"] // 8)
    burst_at = np.linspace(0.15 * D, 0.85 * D, n_bursts)
    for i in range(counts["batch"]):
        g = gnm_random_graph(*BATCH_SHAPE, seed=args.seed + 2000 + i)
        t = float(burst_at[i % n_bursts]) + float(rng.uniform(0, 0.01))
        schedule.append(Arrival(t, "batch", _graph_request(g, "batch")))

    # dup: duplicate-digest storms — each storm is ONE uncached digest
    # fired ~simultaneously; single-flight must answer with one solve.
    n_storms = max(1, counts["dup"] // 6)
    counts["dup"] = n_storms * (counts["dup"] // n_storms)
    storm_at = np.linspace(0.3 * D, 0.7 * D, n_storms)
    for s in range(n_storms):
        g = gnm_random_graph(
            BATCH_SHAPE[0], BATCH_SHAPE[1], seed=args.seed + 3000 + s
        )
        req = _graph_request(g, "dup")
        for k in range(counts["dup"] // n_storms):
            t = float(storm_at[s]) + float(rng.uniform(0, 0.005))
            schedule.append(Arrival(t, "dup", req))

    # update: digest-chained incremental streams (built at dispatch time —
    # each response re-keys the session content-addressed).
    n_streams = 3
    stream_seeds = [
        gnm_random_graph(*UPDATE_SHAPE, seed=args.seed + 4000 + s)
        for s in range(n_streams)
    ]
    for i, t in enumerate(
        arrival_times(counts["update"], D, args.arrival, rng)
    ):
        s = i % n_streams
        n = stream_seeds[s].num_nodes
        a, b = 0, 0
        while a == b:
            a, b = (int(x) for x in rng.integers(0, n, 2))
        kind = "insert" if i % 3 else "reweight"
        upd = {"kind": kind, "u": min(a, b), "v": max(a, b),
               "w": int(rng.integers(1, 100))}
        if kind == "reweight":
            # Reweight an edge that certainly exists: one from the seed.
            j = int(rng.integers(0, stream_seeds[s].num_edges))
            upd["u"] = int(stream_seeds[s].u[j])
            upd["v"] = int(stream_seeds[s].v[j])
        schedule.append(
            Arrival(float(t), "update", stream=s, updates=[upd])
        )

    # oversize: beyond the lane-admission ceiling — must bypass to the
    # single-graph path without stalling small-graph traffic.
    for i, frac in enumerate(np.linspace(0.25, 0.65, counts["oversize"])):
        g = gnm_random_graph(*OVERSIZE_SHAPE, seed=args.seed + 5000 + i)
        schedule.append(
            Arrival(float(frac) * D, "oversize", _graph_request(g, "oversize"))
        )

    schedule.sort(key=lambda a: a.at_s)
    warm_graphs = (
        hit_pool
        + [gnm_random_graph(*s, seed=args.seed + 90) for s in MISS_SHAPES]
        + [gnm_random_graph(*BATCH_SHAPE, seed=args.seed + 91)]
    )
    if counts["oversize"]:  # don't warm a bucket no query will touch
        warm_graphs.append(gnm_random_graph(*OVERSIZE_SHAPE, seed=args.seed + 92))
    return schedule, warm_graphs, stream_seeds, counts


def _stream_window(rng: np.random.Generator, seed_graph, size: int) -> list:
    """One published window, as JSON-ready dicts: the shared seeded
    generator (:func:`stream.window.random_update_stream` — also the
    ``bench.py --update-stream`` workload) with an insert-heavy mix."""
    from distributed_ghs_implementation_tpu.stream.window import (
        random_update_stream,
    )

    window = []
    for upd in random_update_stream(
        rng, seed_graph, size,
        kinds=("insert", "insert", "delete", "reweight"), max_w=200,
    ):
        d = {"kind": upd.kind, "u": upd.u, "v": upd.v}
        if upd.w is not None:
            d["w"] = upd.w
        window.append(d)
    return window


def build_stream_deck(args, rng: np.random.Generator):
    """The ``--update-heavy`` deck: a sustained Poisson stream of window
    publishes against long-lived subscribed graphs, each publish chased by
    a notification poll, over a thin background of cache hits. Returns the
    same ``(schedule, warm_graphs, stream_seeds, counts)`` shape as
    :func:`build_deck`."""
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )

    D = args.duration
    scale = args.rate / 10.0
    shape = _stream_seed_shape(args)
    counts = {
        # Sharded streams publish fewer, heavier windows: each seed solve
        # is a mesh solve and each commit maintains device residency, so
        # the deck trades arrival count for per-window weight.
        "publish": (max(6, int(18 * scale)) if args.sharded_lane
                    else max(9, int(45 * scale))),
        "notify": 0,  # one poll rides along with every publish
        "hit": max(4, int(10 * scale)),
    }
    counts["notify"] = counts["publish"]
    schedule: List[Arrival] = []

    n_streams = args.streams
    stream_seeds = [
        gnm_random_graph(*shape, seed=args.seed + 6000 + s)
        for s in range(n_streams)
    ]
    for i, t in enumerate(
        arrival_times(counts["publish"], D, args.arrival, rng)
    ):
        s = i % n_streams
        schedule.append(Arrival(
            float(t), "publish", stream=s,
            updates=_stream_window(rng, stream_seeds[s],
                                   STREAM_WINDOW_UPDATES),
        ))

    hit_pool = [
        gnm_random_graph(*HIT_SHAPE, seed=args.seed + 100 + i) for i in range(4)
    ]
    for i, t in enumerate(arrival_times(counts["hit"], D, args.arrival, rng)):
        schedule.append(
            Arrival(float(t), "hit", _graph_request(hit_pool[i % 4], "hit"))
        )

    schedule.sort(key=lambda a: a.at_s)
    return schedule, hit_pool, stream_seeds, counts


def _stream_oracle_check(stream_root: str, streams) -> dict:
    """Client-side durability audit, run AFTER the counter snapshots: for
    every stream, rebuild the head from the on-disk snapshot + WAL alone
    (the inheritor's exact recovery path, replayed in this process), then
    solve the rebuilt graph fresh and require the maintained forest
    edge-exact against that oracle. Proves the durable artifacts — not
    just the live sessions — carry every stream through a crash."""
    from distributed_ghs_implementation_tpu.api import (
        minimum_spanning_forest,
    )
    from distributed_ghs_implementation_tpu.stream.log import UpdateLog
    from distributed_ghs_implementation_tpu.stream.window import WindowedMST

    out = {"streams": len(streams), "rebuilt": 0, "head_match": 0,
           "edge_exact": 0}
    for state in streams:
        snap, entries, _notes = UpdateLog(stream_root, state.stream).load()
        if snap is None:
            continue
        mst = WindowedMST.from_state(snap, window_mode="batched")
        chain = snap["digest"]
        intact = True
        for entry in entries:
            if entry["prev"] != chain:
                intact = False
                break
            result, _info = mst.apply_window(entry["updates"])
            chain = result.graph.digest()
            if chain != entry["digest"]:
                intact = False
                break
        if not intact:
            continue
        out["rebuilt"] += 1
        if chain == state.digest:
            out["head_match"] += 1
        rebuilt = mst.result()
        oracle = minimum_spanning_forest(rebuilt.graph, backend="device")
        if np.array_equal(
            np.sort(np.asarray(rebuilt.edge_ids)),
            np.sort(np.asarray(oracle.edge_ids)),
        ):
            out["edge_exact"] += 1
    return out


# ----------------------------------------------------------------------
# Execution
# ----------------------------------------------------------------------
class _SubState:
    """One subscribed stream, client side: the digest-chain head plus the
    subscriber's notification cursor and integrity ledger (every sequence
    number observed via poll — the gap/duplicate check's input)."""

    __slots__ = ("stream", "digest", "lock", "after_seq", "seen", "resets",
                 "head_seq")

    def __init__(self, stream: str, digest: str, seq: int):
        self.stream = stream
        self.digest = digest
        self.lock = threading.Lock()
        self.after_seq = seq
        self.seen: List[int] = []
        self.resets = 0
        self.head_seq = seq


class _StreamState:
    __slots__ = ("digest", "lock", "seed_request")

    def __init__(self, digest: str, seed_request: Optional[dict] = None):
        self.digest = digest
        self.lock = threading.Lock()
        # Fleet mode: a worker crash loses its materialized update
        # sessions; a client re-subscribes by re-solving its seed graph.
        self.seed_request = seed_request


def run_window(service, schedule, streams, args, chaos_plan, arm_chaos):
    """Dispatch the schedule open-loop; returns client-side records + wall.

    Latency is measured from the SCHEDULED arrival instant (not dispatch),
    so client-pool backlog counts against the service — the open-loop
    convention that makes queueing delay visible. ``arm_chaos(plan)``
    applies one chaos-plan entry (in-process fault arming, or fleet-worker
    arming/kill over the control pipe).
    """
    records: List[dict] = []
    records_lock = threading.Lock()

    t0 = time.perf_counter()

    def fire(arrival: Arrival) -> None:
        scheduled = t0 + arrival.at_s
        reset = False
        try:
            if arrival.stream is not None and isinstance(
                streams[arrival.stream], _SubState
            ):
                # --update-heavy: publish one window against the stream
                # head, then poll for its notification — the poll is the
                # subscriber-visible event whose latency the report's
                # "notify" class measures (scheduled arrival -> poll
                # answered), and whose sequence numbers feed the
                # gap/duplicate ledger.
                state = streams[arrival.stream]
                with state.lock:
                    response = service.handle({
                        "op": "publish",
                        "stream": state.stream,
                        "digest": state.digest,
                        "updates": arrival.updates,
                        "slo_class": arrival.cls,
                    })
                    if response.get("ok"):
                        state.digest = response["digest"]
                    elif response.get("stale") and response.get("digest"):
                        # The chain moved under us (a failover replayed
                        # past our head): adopt the reported head. The
                        # window itself may or may not have committed —
                        # the poll below reconciles via sequence numbers.
                        state.digest = response["digest"]
                        state.resets += 1
                        reset = True
                    ok = bool(response.get("ok"))
                    err = response.get("error")
                    publish_done = time.perf_counter()
                    poll = service.handle({
                        "op": "poll",
                        "stream": state.stream,
                        "digest": state.digest,
                        "after_seq": state.after_seq,
                        "slo_class": "notify",
                    })
                    poll_ok = bool(poll.get("ok"))
                    if poll_ok:
                        for note in poll.get("notifications", []):
                            state.seen.append(int(note["seq"]))
                            state.after_seq = max(state.after_seq,
                                                  int(note["seq"]))
                        state.head_seq = max(state.head_seq,
                                             int(poll.get("seq", 0)))
                now = time.perf_counter()
                with records_lock:
                    records.append(
                        {"cls": arrival.cls, "ok": ok, "lost": False,
                         "reset": reset, "error": err,
                         "latency_s": publish_done - scheduled}
                    )
                    records.append(
                        {"cls": "notify", "ok": poll_ok, "lost": False,
                         "reset": False, "extra": True,
                         "error": poll.get("error"),
                         "latency_s": now - scheduled}
                    )
                return
            if arrival.stream is not None:
                state = streams[arrival.stream]
                with state.lock:
                    response = service.handle(
                        {
                            "op": "update",
                            "digest": state.digest,
                            "updates": arrival.updates,
                            "slo_class": arrival.cls,
                        }
                    )
                    if response.get("ok"):
                        state.digest = response["digest"]
                    elif (
                        state.seed_request is not None
                        and "no session" in str(response.get("error", ""))
                    ):
                        # The worker holding this stream's session died:
                        # the update was ANSWERED (not lost), and the
                        # client re-subscribes from its seed graph.
                        reseed = service.handle(dict(state.seed_request))
                        if reseed.get("ok"):
                            state.digest = reseed["digest"]
                            reset = True
            else:
                response = service.handle(arrival.request)
            ok = bool(response.get("ok"))
        except Exception as e:  # noqa: BLE001 — a lost query, recorded
            with records_lock:
                records.append(
                    {"cls": arrival.cls, "ok": False, "lost": True,
                     "error": f"{type(e).__name__}: {e}",
                     "latency_s": time.perf_counter() - scheduled}
                )
            return
        with records_lock:
            records.append(
                {"cls": arrival.cls, "ok": ok, "lost": False, "reset": reset,
                 "error": response.get("error"),
                 "latency_s": time.perf_counter() - scheduled}
            )

    chaos_armed: List[dict] = []
    next_chaos = 0
    with ThreadPoolExecutor(max_workers=args.workers) as pool:
        futures = []
        for arrival in schedule:
            while (
                next_chaos < len(chaos_plan)
                and arrival.at_s >= chaos_plan[next_chaos]["at_s"]
            ):
                # Chaos lands MID-FLIGHT, between dispatches: earlier
                # queries are still in the pool when the faults arm.
                plan = chaos_plan[next_chaos]
                arm_chaos(plan)
                chaos_armed.append(plan)
                next_chaos += 1
            delay = (t0 + arrival.at_s) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            futures.append(pool.submit(fire, arrival))
        for f in futures:
            f.result()  # fire() never raises; this rejoins the pool
    wall_s = time.perf_counter() - t0
    return records, wall_s, chaos_armed


def client_summary(records, wall_s) -> dict:
    """The stopwatch cross-check: same schema, client-side measurements."""
    from distributed_ghs_implementation_tpu.obs import slo

    stats = slo.ClassStats()
    for rec in records:
        stats.observe(rec["cls"], rec["latency_s"], ok=rec["ok"])
    return slo.assemble(stats, wall_s=wall_s)


# ----------------------------------------------------------------------
# The drill
# ----------------------------------------------------------------------
def _read_exported_counters(obs_dir, wid, incarnation) -> Optional[dict]:
    """A drained worker's process counters, recovered from the obs JSONL
    it exported on exit (``worker<K>.<incarnation>.jsonl`` header)."""
    if not obs_dir or incarnation is None:
        return None
    path = os.path.join(obs_dir, f"worker{wid}.{incarnation}.jsonl")
    try:
        from distributed_ghs_implementation_tpu.obs.export import (
            read_events_jsonl,
        )

        _events, meta = read_events_jsonl(path)
    except (OSError, ValueError):
        return None
    counters = meta.get("counters")
    # A file without its trailing totals line (torn export) has no
    # counters — that is a miss, not an empty-but-trustworthy zero.
    return dict(counters) if isinstance(counters, dict) else None


def _fleet_worker_counters(router, obs_dir=None) -> "tuple[dict, List[str]]":
    """Per-``(worker_id, incarnation)`` counter snapshots across the
    fleet's live workers (each worker has its own bus; the router's stats
    op fans out with the incarnation alongside). Also returns the ids of
    live workers that did NOT answer the fan-out — a wedged worker's
    counters silently reading as zero would let the exact-gated checks
    (fresh solves, chain evictions) pass vacuously, so the caller must
    surface a miss as a failed check, never as zeros.

    Keyed by incarnation so window deltas stay honest across a kill: a
    restarted worker is a *new* key with no pre-window baseline, and
    every counter it accumulates — fresh solves included — lands in the
    window delta in full. Subtracting summed totals instead would let
    the victim's vanished pre-kill counters cancel real post-restart
    activity (the drill's "zero fresh solves" gate could pass vacuously)."""
    stats = router.handle({"op": "stats"})
    out, missing = {}, []
    for wid, info in (stats.get("workers") or {}).items():
        if info.get("retired") or info.get("draining"):
            # A planned departure (elastic scale-down) flushed its
            # counters to the obs export on drain — recover them from
            # there so the window delta keeps the retiree's activity. A
            # retiree with no readable export would silently zero out of
            # every exact-gated check (fresh solves, chain evictions), so
            # that is a MISS the caller must surface, never a zero.
            counters = _read_exported_counters(
                obs_dir, wid, info.get("incarnation")
            )
            if counters is None:
                missing.append(f"{wid} (retired, no obs export)")
            else:
                out[(wid, info.get("incarnation"))] = counters
            continue
        wstats = info.get("stats")
        if not wstats:
            missing.append(wid)
            continue
        out[(wid, info.get("incarnation"))] = dict(
            wstats.get("counters") or {}
        )
    return out, missing


def _window_counter_delta(pre: dict, post: dict) -> dict:
    """Summed per-incarnation counter deltas for the measured window."""
    window: dict = {}
    for key, counters in post.items():
        base = pre.get(key, {})
        for name, value in counters.items():
            window[name] = window.get(name, 0) + value - base.get(name, 0)
    return window


def _spawn_listen_workers(n: int):
    """N externally started ``fleet.worker --listen`` echo processes —
    the topology that SURVIVES a router death (``--kill-router`` /
    ``--partition``): a spawned pipe/TCP worker dies with the router's
    pipes, but a --listen worker just returns to accept() with its
    caches warm and waits for the successor to dial."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": os.pathsep.join(
        [root] + os.environ.get("PYTHONPATH", "").split(os.pathsep)
    )}
    procs, addrs = [], []
    for wid in range(n):
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "distributed_ghs_implementation_tpu.fleet.worker",
             "--worker-id", str(wid), "--test-echo",
             "--listen", "127.0.0.1:0"],
            stderr=subprocess.PIPE, env=env,
        )
        line = proc.stderr.readline().decode()
        if "listening on" not in line:
            for p in procs + [proc]:
                p.kill()
            raise RuntimeError(f"worker {wid} never listened: {line!r}")
        procs.append(proc)
        addrs.append(line.rsplit(" ", 1)[-1].strip())
    return procs, addrs


class _RouterProxy:
    """The clients' handle — survives a router swap (``--kill-router``).

    A real deployment's clients reconnect and retry when the router dies;
    here the proxy does the same: a ``router crashed`` response (or a
    request refused during the downtime window) waits for the successor
    and retries ONCE. The retry is safe by the same idempotency the
    worker re-queue relies on: results are content-addressed, so the
    worst case is a warm cache hit for work the journal replay already
    re-ran."""

    def __init__(self, router):
        import threading

        self._router = router
        self._lock = threading.Lock()
        self._ready = threading.Event()
        self._ready.set()
        self.retries = 0

    @property
    def router(self):
        with self._lock:
            return self._router

    def swap_begin(self):
        self._ready.clear()

    def swap(self, new_router):
        with self._lock:
            self._router = new_router
        self._ready.set()

    def handle(self, request: dict) -> dict:
        response = self.router.handle(request)
        err = str(response.get("error", ""))
        if not response.get("ok") and (
            response.get("router_crashed")
            or (not self._ready.is_set() and "shutting down" in err)
        ):
            if self._ready.wait(timeout=120.0):
                with self._lock:
                    self.retries += 1
                response = self.router.handle(request)
        return response

    def __getattr__(self, name):
        # Everything that is not the request path (stats fan-outs,
        # pool_size, arm_worker_fault, shutdown) hits the live router.
        return getattr(self.router, name)


def run_drill(args) -> dict:
    """Run the drill with teardown guaranteed: the fleet drains (flushing
    in-flight responses + per-worker obs exports) and its temporary shared
    store is removed even when the drill body raises."""
    import shutil

    resources: dict = {}
    report = None
    try:
        report = _run_drill(args, resources)
        return report
    finally:
        autoscaler = resources.get("autoscaler")
        if autoscaler is not None:
            autoscaler.close()
        router = resources.get("router")
        if router is not None:
            router.shutdown()
        for proc in resources.get("listen_procs", []):
            try:
                proc.wait(timeout=10)  # shutdown drained it: exit 0
            except Exception:  # noqa: BLE001 — teardown must not raise
                proc.kill()
        # Trace assembly has to wait until here: the drain above is what
        # flushes every worker's JSONL into --trace-dir, and the router's
        # own export should include the retire/drain spans too.
        if report is not None and getattr(args, "trace_dir", None):
            _merge_trace_artifacts(args, report)
        for key in ("disk_tmp", "stream_tmp", "journal_tmp"):
            tmp = resources.get(key)
            if tmp:
                shutil.rmtree(tmp, ignore_errors=True)


def _merge_trace_artifacts(args, report: dict) -> None:
    """Post-drain assembly for ``--trace-dir``: export the router's bus
    beside the workers' JSONL dumps, merge them into one Perfetto trace +
    critical-path report, and promote the join-quality numbers
    (``orphan_spans``, ``traces_joined``) into the gated metrics."""
    import glob

    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.obs.export import (
        write_events_jsonl,
        write_merged_trace,
    )

    write_events_jsonl(
        BUS, os.path.join(args.trace_dir, "router.jsonl"), label="router"
    )
    paths = sorted(
        p for p in glob.glob(os.path.join(args.trace_dir, "*.jsonl"))
        if os.path.basename(p) != "exemplars.jsonl"
    )
    merged = write_merged_trace(
        paths,
        os.path.join(args.trace_dir, "merged_trace.json"),
        os.path.join(args.trace_dir, "critical_path.json"),
    )
    report["trace"] = {
        "dir": args.trace_dir,
        "inputs": [os.path.basename(p) for p in paths],
        "processes": len(merged["processes"]),
        "traces_total": merged["traces_total"],
        "traces_rooted": merged["traces_rooted"],
        "traces_joined": merged["traces_joined"],
        "orphan_spans": merged["orphan_spans"],
        "critical_path": merged["critical_path"]["summary"],
    }
    gate = report.get("gate_metrics")
    if isinstance(gate, dict) and isinstance(gate.get("metrics"), dict):
        gate["metrics"]["orphan_spans"] = merged["orphan_spans"]
        gate["metrics"]["traces_joined"] = merged["traces_joined"]


def _run_drill(args, resources: dict) -> dict:
    import tempfile

    from distributed_ghs_implementation_tpu.obs import slo
    from distributed_ghs_implementation_tpu.obs.events import BUS
    from distributed_ghs_implementation_tpu.obs.export import write_events_jsonl
    from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

    BUS.enable()
    if args.trace_dir:
        os.makedirs(args.trace_dir, exist_ok=True)
        if not args.obs_dir:
            # The per-worker obs JSONL exports double as the trace-merge
            # inputs, so an unset --obs-dir lands them in the trace dir.
            args.obs_dir = args.trace_dir
    rng = np.random.default_rng(args.seed)
    deck = build_stream_deck if args.update_heavy else build_deck
    schedule, warm_graphs, stream_seeds, counts = deck(args, rng)
    stream_tmp = None
    if args.update_heavy:
        # The durable stream layer under test: shared across fleet workers
        # so failover recovery is snapshot+WAL replay, never a re-solve.
        stream_tmp = resources["stream_tmp"] = tempfile.mkdtemp(
            prefix="ghs-stream-log-"
        )

    fleet_router = None
    proxy = None
    listen_addrs = ()
    journal_tmp = None
    disaster = args.kill_router or args.partition is not None
    if args.fleet and disaster:
        # The disaster modes run against externally spawned --listen echo
        # workers: the one topology whose workers OUTLIVE the router, so
        # a router death (or a healed partition) re-adopts the same warm
        # processes instead of cold-spawning new ones.
        procs, listen_addrs = _spawn_listen_workers(args.fleet)
        resources["listen_procs"] = procs
        if args.kill_router:
            journal_tmp = resources["journal_tmp"] = tempfile.mkdtemp(
                prefix="ghs-router-journal-"
            )
    if args.fleet:
        from distributed_ghs_implementation_tpu.fleet.router import (
            FleetConfig,
            FleetRouter,
        )

        resources["disk_tmp"] = tempfile.mkdtemp(prefix="ghs-fleet-store-")
        config = FleetConfig(
            workers=args.fleet,
            remote_workers=tuple(listen_addrs),
            # Durable accepted-work journal: the successor router replays
            # it (--kill-router proves the whole cycle).
            journal_dir=journal_tmp,
            # Transport chaos wrapping (--partition drives it); a short
            # lease so the one-way partition is detected inside the
            # window (the socket never EOFs — silence is the only
            # signal), but not SO short that a healthy worker's read loop
            # stalling on one oversize frame parse reads as silence — the
            # no-lease-trip-on-the-healthy-side check is the point.
            chaos=args.partition is not None,
            heartbeat_interval_s=(
                0.1 if args.partition is not None else 0.25
            ),
            lease_s=(1.5 if args.partition is not None else None),
            # The transport under test: "pipe" (round-12 subprocess pipes)
            # or "tcp" (localhost sockets through the round-16 transport —
            # dial-in hello registration, coalesced pipelined writes,
            # connection-loss death detection). Same deck, same checks;
            # the per-class router_hop_s section is the pipe-vs-TCP
            # overhead number.
            transport=args.transport,
            test_echo=args.test_echo,
            # Mixed-build fleets: the named worker spawns as a legacy
            # build (hello without caps.wire), so its connection degrades
            # binary dispatches to folded JSON while siblings stay opaque.
            worker_env=(
                {args.wire_legacy_worker: {"GHS_FLEET_WIRE": "0"}}
                if args.wire_legacy_worker is not None else None
            ),
            batch_lanes=0 if args.test_echo else args.lanes,
            batch_wait_s=args.batch_wait,
            max_sessions=256,
            store_capacity=max(256, len(schedule)),
            # Bare --sharded-lane: every worker owns a mesh lane and the
            # router steers oversize digests at lane workers.
            sharded_lane_workers=(-1 if args.sharded_lane else 0),
            # The SHARED persistent layer: a restarted worker re-serves its
            # keyspace from disk hits instead of re-solving everything.
            disk_dir=resources["disk_tmp"],
            obs_dir=args.obs_dir,
            request_timeout_s=max(120.0, 12 * args.duration),
            stream_dir=stream_tmp,
            stream_snapshot_every=4,
            # Workers AOT-warm the window kernels for the subscribed shape
            # (and the next edge bucket up, where inserts land) so the
            # first committed window pays no jit tracing.
            warmup_stream_buckets=(
                f"{_stream_seed_shape(args)[0]}x{_stream_seed_shape(args)[1]},"
                f"{_stream_seed_shape(args)[0]}x"
                f"{2 * _stream_seed_shape(args)[1]}"
                if args.update_heavy else None
            ),
        )
        fleet_router = FleetRouter(config).start()
        if args.kill_router:
            proxy = fleet_router = _RouterProxy(fleet_router)
        service = fleet_router
        resources["router"] = fleet_router
    else:
        from distributed_ghs_implementation_tpu.serve.service import MSTService

        service = MSTService(
            batch_lanes=args.lanes,
            batch_wait_s=args.batch_wait,
            max_sessions=256,  # solve seeds must not LRU-evict update sessions
            store_capacity=max(256, len(schedule)),
            sharded_lane=(True if args.sharded_lane == -1
                          else max(0, args.sharded_lane)),
            stream_dir=stream_tmp,
            stream_snapshot_every=4,
        )

    # Warm phase: prime every bucket the deck touches (compiles, rank
    # caches, the hit pool, update sessions) OUTSIDE the measured window —
    # sustained-load numbers should show steady-state serving, and the
    # compile.* counters inside the window then expose any request-time
    # compile as the anomaly it is.
    t_warm = time.perf_counter()
    if args.update_heavy and fleet_router is None:
        from distributed_ghs_implementation_tpu.stream.window import (
            warm_window_kernels,
        )

        warm_window_kernels(*_stream_seed_shape(args))
        warm_window_kernels(
            _stream_seed_shape(args)[0], 2 * _stream_seed_shape(args)[1]
        )
    for g in warm_graphs:
        service.handle(_graph_request(g, "warm"))
    stream_digests = []
    for g in stream_seeds:
        response = service.handle(_graph_request(g, "warm"))
        if not response.get("ok"):
            raise RuntimeError(f"warm solve failed: {response.get('error')}")
        stream_digests.append(response["digest"])
    if args.update_heavy:
        # Subscribe each stream (still inside the warm phase): the seed
        # snapshot lands on disk and the subscriber cursor starts at the
        # returned head sequence.
        streams = []
        for d in stream_digests:
            sub = service.handle(
                {"op": "subscribe", "digest": d, "slo_class": "warm"}
            )
            if not sub.get("ok"):
                raise RuntimeError(f"subscribe failed: {sub.get('error')}")
            streams.append(
                _SubState(sub["stream"], sub["digest"], int(sub["seq"]))
            )
    else:
        streams = [
            _StreamState(
                d,
                seed_request=(
                    _graph_request(g, "update") if fleet_router is not None
                    else None
                ),
            )
            for d, g in zip(stream_digests, stream_seeds)
        ]
    warm_s = time.perf_counter() - t_warm

    # Chaos plan: transient faults armed mid-flight (seeded offsets). The
    # supervisor ladder + batch retry must absorb them — degraded latency
    # is expected, lost accepted queries are not. In fleet mode the faults
    # arm INSIDE the workers over the control pipe; ``--kill-worker`` adds
    # the fleet.worker.crash entry (the worker dies in place of its next
    # request — no response flushed, the router must re-queue).
    chaos_plan = []
    if not args.no_chaos:
        chaos_plan.append(
            {
                "at_s": 0.5 * args.duration,
                "sites": {"resilience.attempt.device": 2, "batch.attempt": 1},
            }
        )
        if args.chaos:
            chaos_plan.append(
                {
                    "at_s": 0.7 * args.duration,
                    "sites": {"resilience.attempt.device": 4, "batch.attempt": 2},
                }
            )
    if fleet_router is not None and args.kill_worker is not None:
        chaos_plan.append(
            {"at_s": 0.45 * args.duration, "kill_worker": args.kill_worker}
        )
    if fleet_router is not None and args.kill_router:
        chaos_plan.append({"at_s": 0.45 * args.duration, "kill_router": True})
    if fleet_router is not None and args.partition is not None:
        chaos_plan.append(
            {"at_s": 0.45 * args.duration, "partition": args.partition}
        )
    chaos_plan.sort(key=lambda plan: plan["at_s"])

    crash_info: dict = {}

    def do_router_crash() -> None:
        """Kill the router with accepted work provably outstanding, then
        boot its successor on the same journal + worker endpoints."""
        from distributed_ghs_implementation_tpu.fleet.journal import (
            RouterJournal,
        )
        from distributed_ghs_implementation_tpu.fleet.router import (
            FleetRouter,
        )

        old = proxy.router
        pre_stats = old.handle({"op": "stats"})
        crash_info["pre_handled"] = (
            pre_stats.get("counters", {}).get("echo.handled", 0)
        )
        # Guarantee accepted-work-outstanding at the crash instant: one
        # slow echo solve is in flight (journaled, unanswered) when the
        # router dies — the exact shape the journal exists for.
        slow = threading.Thread(target=service.handle, args=(
            {"op": "solve", "digest": f"orphan-{args.seed}",
             "sleep_s": 1.5, "slo_class": "miss"},
        ), daemon=True)
        slow.start()
        crash_info["extra_requests"] = 1
        time.sleep(0.3)
        proxy.swap_begin()
        t0 = time.perf_counter()
        old.crash()
        crash_info["orphans_at_crash"] = len(
            RouterJournal(journal_tmp).load().unanswered
        )
        successor = FleetRouter(config).start()
        proxy.swap(successor)
        crash_info["restart_s"] = time.perf_counter() - t0

    def do_partition(victim: int) -> None:
        fleet_router.partition_worker(victim, mode="oneway")
        crash_info["partitioned_at"] = time.perf_counter()

        def heal() -> None:
            fleet_router.heal_partition(victim)
            crash_info["healed_at"] = time.perf_counter()

        timer = threading.Timer(args.partition_duration, heal)
        timer.daemon = True
        timer.start()

    def arm_chaos(plan: dict) -> None:
        if fleet_router is not None:
            for site, times in plan.get("sites", {}).items():
                for wid in range(args.fleet):
                    fleet_router.arm_worker_fault(wid, site=site, times=times)
            if "kill_worker" in plan:
                fleet_router.arm_worker_fault(
                    plan["kill_worker"], site="fleet.worker.crash", times=1
                )
            if plan.get("kill_router"):
                # The crash + successor boot runs off-thread so arrivals
                # keep firing THROUGH the outage (that is the test).
                threading.Thread(
                    target=do_router_crash, name="drill-router-crash",
                    daemon=True,
                ).start()
            if "partition" in plan:
                do_partition(plan["partition"])
        else:
            for site, times in plan.get("sites", {}).items():
                FAULTS.arm(site, times=times)

    # A pre-window stats miss is the SAFE direction (the delta over-counts
    # that worker), so it doesn't gate; a post-window miss does.
    pre_window = (
        _fleet_worker_counters(fleet_router, args.obs_dir)[0]
        if fleet_router is not None
        else {}
    )
    BUS.clear()  # the measured window starts here
    autoscaler = None
    elastic_policy = None
    if fleet_router is not None and args.elastic:
        from distributed_ghs_implementation_tpu.fleet.autoscaler import (
            Autoscaler,
            ElasticPolicy,
        )

        # Deterministic by construction: a ZERO wait budget means any
        # class-tagged request breaches, so the ramp provokes exactly
        # (max - fleet) scale-ups (one per cooldown, stopping at max) and
        # post-window idle drains exactly (max - min) retires. The drill
        # is proving the machinery — warm joins, lossless retires — not
        # tuning thresholds; production budgets live in ElasticPolicy
        # defaults / the serve CLI flags.
        elastic_policy = ElasticPolicy(
            min_workers=(args.elastic_min
                         if args.elastic_min is not None
                         else max(1, args.fleet - 1)),
            max_workers=(args.elastic_max
                         if args.elastic_max is not None
                         else args.fleet + 1),
            tick_s=0.25,
            cooldown_s=1.0,
            wait_budget_s=0.0,
            idle_ticks=10,  # 2.5s of silence = the window is over
        )
        autoscaler = Autoscaler(fleet_router, elastic_policy).start()
        resources["autoscaler"] = autoscaler
    try:
        records, wall_s, chaos_armed = run_window(
            service, schedule, streams, args, chaos_plan, arm_chaos
        )
    finally:
        FAULTS.reset()

    # Elastic convergence: the up decisions fire during the ramp, but a
    # warm join (spawn + pre-seed + warmup ladder) may outlive the window
    # — wait for the pool to reach max, then for post-window idle to
    # drain it back to min, then STOP the autoscaler so the recovery
    # probes below (real traffic) cannot provoke extra scale events and
    # break the exact counts the gate pins.
    elastic = None
    if autoscaler is not None:
        def _wait_pool(target: int, timeout_s: float) -> bool:
            deadline = time.perf_counter() + timeout_s
            while time.perf_counter() < deadline:
                if fleet_router.pool_size() == target:
                    return True
                time.sleep(0.1)
            return fleet_router.pool_size() == target

        reached_max = _wait_pool(elastic_policy.max_workers, 240.0)
        reached_min = _wait_pool(elastic_policy.min_workers, 120.0)
        autoscaler.close()
        # Let an in-flight retire's accounting land before counters read.
        deadline = time.perf_counter() + 10.0
        expected_downs = (
            elastic_policy.max_workers - elastic_policy.min_workers
        )
        while (time.perf_counter() < deadline
               and BUS.counters().get("fleet.scale.down", 0)
               < expected_downs):
            time.sleep(0.05)
        elastic = {
            "policy": {
                "min_workers": elastic_policy.min_workers,
                "max_workers": elastic_policy.max_workers,
                "cooldown_s": elastic_policy.cooldown_s,
                "idle_ticks": elastic_policy.idle_ticks,
            },
            "reached_max": reached_max,
            "reached_min": reached_min,
            "final_pool": fleet_router.pool_size(),
            "decisions": list(autoscaler.decisions),
        }

    # Kill-drill recovery: wait for the dead worker to restart and rejoin
    # the ring, then drive a probe query onto it — "goodput recovery" is a
    # query actually served by the restarted process, not just a counter.
    # In elastic mode the scale-down may legitimately have RETIRED the
    # restarted victim (a fresh incarnation has the least affinity), so
    # the ring-heal + probe-at-victim checks don't apply there; the
    # elastic checks pin pool convergence instead.
    rejoined = None
    probe = None
    if (fleet_router is not None and args.kill_worker is not None
            and not args.elastic):
        rejoined = False
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            stats = fleet_router.handle({"op": "stats"})
            if sorted(stats.get("ring", [])) == list(range(args.fleet)):
                rejoined = True
                break
            time.sleep(0.25)
        if rejoined and not args.update_heavy:
            from distributed_ghs_implementation_tpu.fleet.hashing import (
                HashRing,
            )
            from distributed_ghs_implementation_tpu.graphs.generators import (
                gnm_random_graph,
            )

            ring = HashRing(
                range(args.fleet),
                replicas=fleet_router.config.ring_replicas,
            )
            hint = next(
                f"probe-{i}" for i in range(10_000)
                if ring.assign(f"probe-{i}") == args.kill_worker
            )
            probe_req = _graph_request(
                gnm_random_graph(*HIT_SHAPE, seed=args.seed + 7), "probe"
            )
            probe_req["digest"] = hint  # route straight at the rejoiner
            probe = service.handle(probe_req)

    # Router-crash recovery (--kill-router): wait for the successor's
    # journal replay to answer every orphaned accept, then read the
    # warm-re-adoption evidence (same worker processes => echo.handled
    # persists across the crash).
    router_recovery = None
    if fleet_router is not None and args.kill_router:
        stats = {}
        deadline = time.perf_counter() + 60.0
        while time.perf_counter() < deadline:
            stats = fleet_router.handle({"op": "stats"})
            if stats.get("journal", {}).get("unanswered", 1) == 0:
                break
            time.sleep(0.1)
        counters_bus = BUS.counters()
        router_recovery = {
            "restart_s": crash_info.get("restart_s"),
            "orphans_at_crash": crash_info.get("orphans_at_crash", 0),
            "journal_unanswered": stats.get("journal", {}).get(
                "unanswered", -1
            ),
            "journal_accepted": stats.get("journal", {}).get("accepted", 0),
            "pre_handled": crash_info.get("pre_handled", 0),
            "post_handled": stats.get("counters", {}).get(
                "echo.handled", 0
            ),
            "readopted": int(
                counters_bus.get("fleet.router.restart.readopted", 0)
            ),
            "requeued": int(
                counters_bus.get("fleet.router.restart.requeued", 0)
            ),
            "replayed": int(
                counters_bus.get("fleet.router.restart.replayed", 0)
            ),
            "crashes": int(counters_bus.get("fleet.router.crash", 0)),
            "client_retries": proxy.retries,
            "ring": sorted(stats.get("ring", [])),
        }

    # Partition recovery (--partition): wait for the healed link's redial
    # to put the victim back on the ring, then read the healthy-side
    # evidence (survivors never restarted, never tripped a lease).
    partition_recovery = None
    if fleet_router is not None and args.partition is not None:
        victim = args.partition
        stats = {}
        deadline = time.perf_counter() + 60.0
        while time.perf_counter() < deadline:
            stats = fleet_router.handle({"op": "stats"})
            if sorted(stats.get("ring", [])) == list(range(args.fleet)):
                break
            time.sleep(0.1)
        counters_bus = BUS.counters()
        workers_out = stats.get("workers") or {}
        partition_recovery = {
            "victim": victim,
            "mode": "oneway",
            "duration_s": args.partition_duration,
            "ring_healed": sorted(stats.get("ring", []))
            == list(range(args.fleet)),
            "victim_restarts": int(
                workers_out.get(str(victim), {}).get("restarts", 0)
            ),
            "healthy_restarts": sum(
                int(info.get("restarts", 0))
                for wid, info in workers_out.items()
                if int(wid) != victim
            ),
            "lease_expired": int(
                counters_bus.get("fleet.lease.expired", 0)
            ),
            "frames_dropped": int(
                counters_bus.get("fleet.chaos.dropped", 0)
            ),
            "post_handled": stats.get("counters", {}).get(
                "echo.handled", 0
            ),
        }

    # Stream recovery + drain (--update-heavy): after a kill, one more
    # published window per stream proves the restarted fleet serves the
    # chain (recovery = snapshot+WAL replay, asserted below via the
    # stream.replay.* / fresh-solve counters); then a final poll per
    # stream drains remaining notifications so the gap/duplicate ledger
    # is complete through the head sequence.
    recovery = None
    stream_drain = 0
    notify_gaps = notify_dups = drain_errors = 0
    if args.update_heavy:
        from distributed_ghs_implementation_tpu.stream.session import (
            poll_gap_check,
        )

        if fleet_router is not None and (
            args.kill_worker is not None or args.elastic
        ):
            # After a kill OR an elastic retire, the streams' pins moved:
            # one more published window per stream proves the inheritor
            # serves the chain (recovered by snapshot+WAL replay, never a
            # fresh solve — the counters below assert it).
            recovery = []
            for s, state in enumerate(streams):
                t_r = time.perf_counter()
                attempts = 0
                with state.lock:
                    for _attempt in range(2):
                        attempts += 1
                        resp = service.handle({
                            "op": "publish",
                            "stream": state.stream,
                            "digest": state.digest,
                            "updates": _stream_window(
                                rng, stream_seeds[s], STREAM_WINDOW_UPDATES
                            ),
                            "slo_class": "publish",
                        })
                        if resp.get("stale") and resp.get("digest"):
                            # The crash landed between WAL append and the
                            # response: the window IS committed and replay
                            # moved the head past ours. Adopt and retry.
                            state.digest = resp["digest"]
                            continue
                        if resp.get("ok"):
                            state.digest = resp["digest"]
                        break
                recovery.append({
                    "ok": bool(resp.get("ok")),
                    "recover_s": time.perf_counter() - t_r,
                    "worker": resp.get("worker"),
                    "requests": attempts,
                })
        for state in streams:
            with state.lock:
                poll = service.handle({
                    "op": "poll",
                    "stream": state.stream,
                    "digest": state.digest,
                    "after_seq": state.after_seq,
                    "slo_class": "notify",
                })
                stream_drain += 1
                if poll.get("ok"):
                    for note in poll.get("notifications", []):
                        state.seen.append(int(note["seq"]))
                        state.after_seq = max(
                            state.after_seq, int(note["seq"])
                        )
                    state.head_seq = max(
                        state.head_seq, int(poll.get("seq", 0))
                    )
                else:
                    # A failed drain leaves state.head_seq stale, which
                    # would let poll_gap_check pass vacuously — count it
                    # so the gap/duplicate verdict can't silently rest on
                    # an incomplete ledger.
                    drain_errors += 1
            check = poll_gap_check(state.seen, state.head_seq)
            notify_gaps += check["gaps"]
            notify_dups += check["dups"]

    # Server-side accounting: the per-class join over real bus events (the
    # router's fleet.request spans in fleet mode — which then carry the
    # per-worker breakdown).
    summary = slo.summarize_bus(BUS, wall_s=wall_s)
    client = client_summary(records, wall_s)
    router_hop = {}
    if fleet_router is not None:
        # Router-hop latency (send-to-response minus in-worker service
        # time — transport + queueing overhead) joins the shared SLO
        # section per class, so pipe-vs-TCP cost is a tracked number in
        # every fleet report.
        for name, hist in BUS.histograms().items():
            if not name.startswith("fleet.hop_s") or not hist.get("count"):
                continue
            cls = name[len("fleet.hop_s."):] if name != "fleet.hop_s" else None
            if cls is None:
                summary["totals"]["router_hop_s"] = hist
                router_hop = hist
            elif cls in summary["classes"]:
                summary["classes"][cls]["router_hop_s"] = hist
    if fleet_router is not None:
        # Worker counters live in the worker processes; the window's share
        # is the post-minus-pre delta per (worker, incarnation). A killed
        # worker's pre-kill counters die with it (unobservable), but its
        # restarted incarnation starts from a zero baseline, so anything
        # it does during the window — a fresh solve where replay was
        # promised — shows up undiminished.
        post_window, stats_missing = _fleet_worker_counters(
            fleet_router, args.obs_dir
        )
        window_counters = _window_counter_delta(pre_window, post_window)
        fleet_counters = {
            k: v for k, v in BUS.counters().items() if k.startswith("fleet.")
        }
    else:
        window_counters = dict(BUS.counters())
        fleet_counters = {}
        stats_missing = []
    compile_counters = {
        k: v for k, v in window_counters.items() if k.startswith("compile.")
    }
    serve_counters = {
        k: v
        for k, v in window_counters.items()
        if k.startswith(("serve.", "batch.", "stream."))
    }
    if args.jsonl:
        write_events_jsonl(BUS, args.jsonl)

    # Durable-artifact oracle audit (after the counter snapshots above,
    # so its own apply/solve traffic cannot pollute the gated windows).
    stream_oracle = None
    if args.update_heavy and stream_tmp is not None:
        stream_oracle = _stream_oracle_check(stream_tmp, streams)

    # "extra" records are the chase polls riding publish arrivals — they
    # count toward latency/error accounting but not toward the
    # one-record-per-scheduled-arrival invariant.
    base_records = [rec for rec in records if not rec.get("extra")]
    lost = sum(1 for rec in records if rec["lost"])
    answered = len(base_records)
    resets = sum(1 for rec in records if rec.get("reset"))
    errors = sum(
        1 for rec in records
        if not rec["ok"] and not rec["lost"] and not rec.get("reset")
    )
    fresh_solves = window_counters.get("serve.scheduler.fresh_solve", 0)
    expected_classes = [c for c, n in counts.items() if n > 0]
    bus_classes = summary["classes"]

    # Every scheduled arrival, plus the out-of-schedule requests the drill
    # itself makes (chase polls, session re-subscribe solves, the
    # post-kill recovery probes, final drain polls), must appear as
    # exactly one request span.
    expected_spans = len(schedule) + resets + (1 if probe is not None else 0)
    if fleet_router is not None and args.kill_router:
        # The crash thread's deliberate in-flight orphan, plus one extra
        # span per client retry (the failed pre-crash attempt and its
        # post-restart retry are separate fleet.request spans).
        expected_spans += crash_info.get("extra_requests", 0) + proxy.retries
    if args.update_heavy:
        expected_spans = (
            len(schedule)
            + sum(1 for rec in records if rec.get("extra"))  # chase polls
            + stream_drain
            + (sum(r["requests"] for r in recovery) if recovery else 0)
        )
    checks = [
        ("every accepted query answered",
         answered == len(schedule) and lost == 0),
        ("all classes present in the bus-joined report",
         all(c in bus_classes for c in expected_classes)),
        ("bus join saw every request span",
         summary["totals"]["sent"] == expected_spans),
        ("no events dropped during the window (report trustworthy)",
         not summary["dropped_warning"]),
        ("chaos armed mid-flight", len(chaos_armed) == len(chaos_plan)),
    ]
    if fleet_router is not None:
        # A live worker whose stats fan-out failed contributes ZERO to
        # the window delta — every exact-gated counter check below would
        # pass vacuously, so a miss is a drill failure, not a zero.
        checks.append((
            "post-window stats from every live worker (counter gates "
            "trustworthy)", not stats_missing,
        ))
    if fleet_router is not None and args.wire == "binary":
        wire_pass = fleet_counters.get("fleet.wire.passthrough", 0)
        wire_fb = fleet_counters.get("fleet.wire.fallback_json", 0)
        checks.append(
            ("binary solve dispatches rode the wire plane", wire_pass >= 1)
        )
        if args.wire_legacy_worker is None:
            checks.append(
                ("no JSON fallback in an all-binary fleet", wire_fb == 0)
            )
        else:
            # The mixed-build contract: the legacy worker's ring share
            # degrades per connection (folded JSON), never errors — and
            # the capable workers keep the opaque path.
            checks.append(
                ("legacy worker's share degraded to folded JSON",
                 wire_fb >= 1)
            )
    if args.update_heavy:
        checks += [
            ("zero errors (stale head re-syncs excluded)", errors == 0),
            ("p99 bounded under sustained update load",
             client["totals"]["latency_s"].get("p99", float("inf"))
             <= args.p99_bound),
            ("no lost or duplicated window notifications",
             notify_gaps == 0 and notify_dups == 0),
            ("final drain polls all answered (gap ledger complete)",
             drain_errors == 0),
            ("windows applied batched, never degraded to resolve",
             window_counters.get("stream.window.batched", 0) >= 1
             and window_counters.get("stream.window.resolve", 0) == 0),
            ("superseded chain ancestors evicted from the LRU",
             window_counters.get("serve.store.chain_evicted", 0) >= 1),
            ("zero fresh solves while streams were live",
             fresh_solves == 0),
        ]
        if stream_oracle is not None:
            checks += [
                ("durable log rebuilds every stream head "
                 "(snapshot+WAL alone)",
                 stream_oracle["rebuilt"] == len(streams)
                 and stream_oracle["head_match"] == len(streams)),
                ("post-replay heads edge-exact against a fresh oracle "
                 "solve",
                 stream_oracle["edge_exact"] == len(streams)),
            ]
        if args.sharded_lane:
            checks.append(
                ("published windows migrated mesh residency (donated "
                 "scatter or bounded restage, never dropped)",
                 (window_counters.get("stream.lane.migrated", 0)
                  + window_counters.get("stream.lane.restaged", 0)) >= 1
                 and window_counters.get("lane.update.dropped", 0) == 0),
            )
        if fleet_router is not None and args.kill_worker is not None:
            checks += [
                ("worker killed mid-stream",
                 fleet_counters.get("fleet.worker.dead", 0) >= 1),
                ("dead worker restarted with backoff",
                 fleet_counters.get("fleet.worker.restart", 0) >= 1),
                ("streams recovered by snapshot+WAL replay (no re-solve)",
                 window_counters.get("stream.replay.streams", 0) >= 1),
                ("post-recovery window publishes served",
                 recovery is not None
                 and all(r["ok"] for r in recovery)),
            ]
            if args.sharded_lane:
                checks.append(
                    ("sharded residency rebuilt on replay (re-staged and "
                     "re-scattered, never unavailable)",
                     window_counters.get(
                         "stream.replay.residency_restored", 0) >= 1
                     and window_counters.get(
                         "stream.replay.residency_unavailable", 0) == 0),
                )
            if not args.elastic:  # elastic pins pool convergence instead
                checks.append(
                    ("fleet healed: full ring after the drill",
                     bool(rejoined)),
                )
        elif fleet_router is not None:
            checks += [
                ("no unplanned worker deaths",
                 fleet_counters.get("fleet.worker.dead", 0) == 0),
            ]
            if args.elastic:
                checks += [
                    ("retired workers' streams migrated by WAL replay "
                     "(no re-solve)",
                     window_counters.get("stream.replay.streams", 0) >= 1),
                    ("post-retire window publishes served by inheritors",
                     recovery is not None
                     and all(r["ok"] for r in recovery)),
                ]
    elif fleet_router is None:
        checks += [
            ("zero errors (chaos absorbed by the supervisor)", errors == 0),
            ("p99 bounded under chaos",
             client["totals"]["latency_s"].get("p99", float("inf"))
             <= args.p99_bound),
            ("duplicate storms coalesced (single-flight)",
             serve_counters.get("serve.scheduler.coalesced", 0) >= 1),
            ("cache absorbed the hit class",
             serve_counters.get("serve.store.hit", 0) >= counts["hit"]),
            ("zero request-time compiles in the measured window",
             compile_counters.get("compile.miss", 0) == 0),
        ]
        if args.oversize_heavy:
            interactive_p99 = max(
                bus_classes.get(c, {}).get("latency_s", {}).get("p99", 0.0)
                for c in ("hit", "dup")
            )
            checks.append(
                ("interactive p99 protected under concurrent bulk load",
                 interactive_p99 <= args.interactive_p99_bound),
            )
            if args.sharded_lane:
                checks.append(
                    ("oversize solves rode the mesh lane",
                     serve_counters.get("serve.route.sharded_lane", 0)
                     >= counts["oversize"]),
                )
    else:
        checks += [
            ("zero errors beyond session re-subscribes", errors == 0),
            ("p99 bounded under failover (degraded but bounded)",
             client["totals"]["latency_s"].get("p99", float("inf"))
             <= args.p99_bound),
            ("per-worker SLO breakdown present",
             bool(summary.get("workers"))),
        ]
        if args.kill_worker is not None:
            checks += [
                ("worker killed mid-traffic",
                 fleet_counters.get("fleet.worker.dead", 0) >= 1),
                ("accepted requests re-queued onto survivors",
                 fleet_counters.get("fleet.requeue", 0) >= 1),
                ("dead worker restarted with backoff",
                 fleet_counters.get("fleet.worker.restart", 0) >= 1),
            ]
            if not args.elastic:
                # Elastic scale-down may legitimately retire the restarted
                # victim (a fresh incarnation has the least affinity) —
                # pool convergence is the elastic heal check instead.
                checks += [
                    ("fleet healed: full ring after the drill",
                     bool(rejoined)),
                    ("restarted worker serves traffic (goodput recovery)",
                     bool(probe and probe.get("ok")
                          and probe.get("worker") == args.kill_worker)),
                ]
        elif args.kill_router:
            checks += [
                ("router crashed mid-flight with accepted work outstanding",
                 router_recovery["crashes"] == 1
                 and router_recovery["orphans_at_crash"] >= 1),
                ("journal replay answered every accepted query",
                 router_recovery["journal_unanswered"] == 0
                 and router_recovery["requeued"] >= 1),
                ("workers re-adopted warm (handled counts persist)",
                 router_recovery["readopted"] == args.fleet
                 and router_recovery["post_handled"]
                 >= router_recovery["pre_handled"]),
                ("full ring after router restart",
                 router_recovery["ring"] == list(range(args.fleet))),
                ("no worker died in the router crash (their processes "
                 "outlive the router)",
                 fleet_counters.get("fleet.worker.dead", 0) == 0),
            ]
        elif args.partition is not None:
            checks += [
                ("partition armed and healed",
                 fleet_counters.get("fleet.chaos.partition", 0) == 1
                 and fleet_counters.get("fleet.chaos.heal", 0) == 1),
                ("victim's link went dark (frames dropped, socket open)",
                 fleet_counters.get("fleet.chaos.dropped", 0) >= 1),
                ("ring healed after the partition (warm rejoin)",
                 partition_recovery["ring_healed"]),
                ("no lease trip on the healthy side (zero survivor "
                 "restarts)",
                 partition_recovery["healthy_restarts"] == 0),
                ("exactly one answer per accepted query (idempotent "
                 "re-queue, no duplicates)",
                 answered == len(schedule)),
            ]
        else:
            # No kill: the fleet must ride the window without ANY failover.
            checks += [
                ("no unplanned worker deaths",
                 fleet_counters.get("fleet.worker.dead", 0) == 0),
            ]
            if not args.elastic:
                # A joiner entering mid-window changes routing, so fresh
                # digests can land on it cold — zero request-time compiles
                # is a steady-state-pool property.
                checks.append(
                    ("zero request-time compiles in the measured window",
                     compile_counters.get("compile.miss", 0) == 0),
                )
    if elastic is not None:
        # Exact by construction: ups stop at max_workers, downs stop at
        # min_workers, cooldown serializes events, and the autoscaler was
        # stopped before the recovery traffic — so the counts are a
        # property of the policy, not the machine (gated exactly).
        expected_ups = elastic_policy.max_workers - args.fleet
        expected_downs = (
            elastic_policy.max_workers - elastic_policy.min_workers
        )
        scale_ups = int(fleet_counters.get("fleet.scale.up", 0))
        scale_downs = int(fleet_counters.get("fleet.scale.down", 0))
        join_hist = BUS.histograms().get("fleet.join.warm_s", {})
        checks += [
            ("fleet grew to max under load (exact scale-up events)",
             elastic["reached_max"] and scale_ups == expected_ups),
            ("fleet drained back to min on idle (exact scale-down events)",
             elastic["reached_min"] and scale_downs == expected_downs
             and elastic["final_pool"] == elastic_policy.min_workers),
            ("every joiner entered the ring warm (warmed hello confirmed)",
             fleet_counters.get("fleet.join.cold_rejected", 0) == 0
             and join_hist.get("count", 0) == scale_ups),
        ]
    ok = all(passed for _, passed in checks)

    if args.update_heavy:
        if fleet_router is None:
            workload = (WORKLOAD_STREAM_SHARDED if args.sharded_lane
                        else WORKLOAD_STREAM)
        elif args.kill_worker is not None:
            workload = (WORKLOAD_STREAM_SHARDED_KILL if args.sharded_lane
                        else WORKLOAD_STREAM_KILL)
        elif args.elastic:
            workload = WORKLOAD_FLEET_ELASTIC
        else:
            workload = WORKLOAD_STREAM_FLEET
    elif fleet_router is None:
        workload = WORKLOAD_OVERSIZE if args.oversize_heavy else WORKLOAD
    elif args.kill_router:
        workload = WORKLOAD_FLEET_ROUTER
    elif args.partition is not None:
        workload = WORKLOAD_FLEET_PARTITION
    elif args.kill_worker is not None:
        workload = (WORKLOAD_FLEET_ELASTIC_KILL if args.elastic
                    else WORKLOAD_FLEET_KILL)
    elif args.elastic:
        workload = WORKLOAD_FLEET_ELASTIC
    else:
        workload = WORKLOAD_FLEET
    config = {
        "workload": workload,
        "deck": "smoke" if args.smoke else "custom",
        "seed": args.seed,
        "arrival": args.arrival,
        "duration_s": args.duration,
        "rate": args.rate,
        "lanes": args.lanes,
        "counts": counts,
        "chaos": "off" if args.no_chaos else ("heavy" if args.chaos else "mid"),
    }
    if args.wire != "json":
        # Only stamped off the default so existing baselines' config
        # blocks keep matching byte-for-byte.
        config["wire"] = args.wire
        if args.wire_legacy_worker is not None:
            config["wire_legacy_worker"] = args.wire_legacy_worker
    if args.oversize_heavy:
        config["oversize_heavy"] = True
        config["sharded_lane"] = bool(args.sharded_lane)
    if args.update_heavy:
        config["update_heavy"] = True
        config["streams"] = args.streams
        config["window_updates"] = STREAM_WINDOW_UPDATES
        if args.sharded_lane:
            config["sharded_lane"] = True
            config["stream_shape"] = list(STREAM_SHARDED_SHAPE)
    if args.fleet:
        config["fleet"] = args.fleet
        config["kill_worker"] = args.kill_worker
        config["transport"] = args.transport
        if args.kill_router:
            config["kill_router"] = True
        if args.partition is not None:
            config["partition"] = args.partition
            config["partition_duration_s"] = args.partition_duration
        if args.test_echo:
            config["test_echo"] = True
        if elastic is not None:
            config["elastic"] = elastic["policy"]
    extra_metrics = {"lost_accepted": lost, "answered": answered}
    if router_hop:
        extra_metrics["router_hop_p50_s"] = router_hop.get("p50", 0.0)
        extra_metrics["router_hop_p95_s"] = router_hop.get("p95", 0.0)
    if args.update_heavy:
        extra_metrics["notify_gaps"] = notify_gaps
        extra_metrics["notify_dups"] = notify_dups
        extra_metrics["drain_errors"] = drain_errors
        extra_metrics["stream_resets"] = sum(s.resets for s in streams)
        extra_metrics["fresh_solves"] = fresh_solves
        if stream_oracle is not None:
            extra_metrics["oracle_exact"] = stream_oracle["edge_exact"]
        if args.sharded_lane:
            extra_metrics["residency_restored"] = window_counters.get(
                "stream.replay.residency_restored", 0
            )
            extra_metrics["residency_migrated"] = (
                window_counters.get("stream.lane.migrated", 0)
                + window_counters.get("stream.lane.restaged", 0)
            )
        if recovery:
            extra_metrics["replay_recovery_s"] = max(
                r["recover_s"] for r in recovery
            )
    if fleet_router is not None:
        if not args.update_heavy:
            extra_metrics["session_resets"] = resets
        extra_metrics["worker_restarts"] = fleet_counters.get(
            "fleet.worker.restart", 0
        )
        extra_metrics["requeued"] = fleet_counters.get("fleet.requeue", 0)
    if router_recovery is not None:
        # Exact by construction: one deliberate crash, a journal replay
        # that must drain to zero, and every --listen worker re-adopted
        # warm. fresh_solves pins the pinned-session contract (echo
        # fleets trivially report 0; a real fleet would pay a fresh
        # solve only if re-adoption silently went cold).
        extra_metrics["router_crashes"] = router_recovery["crashes"]
        extra_metrics["journal_unanswered"] = (
            router_recovery["journal_unanswered"]
        )
        extra_metrics["workers_readopted"] = router_recovery["readopted"]
        extra_metrics["fresh_solves"] = fresh_solves
        extra_metrics["router_restart_s"] = round(
            router_recovery.get("restart_s") or 0.0, 4
        )
    if partition_recovery is not None:
        extra_metrics["healthy_restarts"] = (
            partition_recovery["healthy_restarts"]
        )
        extra_metrics["frames_dropped"] = (
            partition_recovery["frames_dropped"]
        )
    if elastic is not None:
        extra_metrics["scale_up_events"] = scale_ups
        extra_metrics["scale_down_events"] = scale_downs
        if join_hist.get("count"):
            # The warm-join wall time (spawn -> pre-seed -> warmup ladder
            # -> warmed hello -> ring entry); its p95 gates as a ceiling.
            extra_metrics["fleet_join_warm_p95_s"] = join_hist["p95"]
    gate = slo.gate_metrics(
        summary,
        workload=workload,
        config=config,
        extra_metrics=extra_metrics,
    )
    if args.kill_router:
        # The whole latency envelope of this drill is downtime-dominated
        # and thread-timing shaped: WHICH class absorbs the ~1s outage
        # stall (and how many first attempts land inside it and retry) is
        # a lottery, so per-class p99/goodput/error numbers stay
        # report-only — the same reasoning that keeps the worker-kill
        # drill off a latency baseline. The gate pins the deterministic
        # survivability contract exactly.
        keep = {
            "lost_accepted", "answered", "session_resets",
            "worker_restarts", "requeued", "router_crashes",
            "journal_unanswered", "workers_readopted", "fresh_solves",
        }
        gate["metrics"] = {
            k: v for k, v in gate["metrics"].items() if k in keep
        }
    report = {
        "schema": REPORT_SCHEMA,
        "config": config,
        "wall_s": round(wall_s, 3),
        "warm_s": round(warm_s, 3),
        "slo": summary,
        "client": client,
        "compile_counters": compile_counters,
        "serve_counters": serve_counters,
        "chaos": {
            "armed": chaos_armed,
            "lost_accepted": lost,
            "errors": errors,
        },
        "events_dropped": summary["events_dropped"],
        "dropped_warning": summary["dropped_warning"],
        "checks": [{"name": n, "ok": bool(p)} for n, p in checks],
        "ok": ok,
        "gate_metrics": gate,
    }
    if args.update_heavy:
        report["stream"] = {
            "streams": args.streams,
            "notify_gaps": notify_gaps,
            "notify_dups": notify_dups,
            "drain_errors": drain_errors,
            "stream_resets": sum(s.resets for s in streams),
            "fresh_solves": fresh_solves,
            "head_seqs": {s.stream: s.head_seq for s in streams},
            "recovery": recovery,
            "oracle": stream_oracle,
        }
    if fleet_router is not None:
        report["fleet"] = {
            "workers": args.fleet,
            "transport": args.transport,
            "counters": fleet_counters,
            "session_resets": resets,
            "rejoined": rejoined,
            "probe": probe,
        }
        if router_recovery is not None:
            report["router"] = router_recovery
        if partition_recovery is not None:
            report["partition"] = partition_recovery
        if elastic is not None:
            # The elastic trace: policy, convergence, and the decision
            # log (action + reason + pool size per scale event) — the
            # "fleet grew and shrank across the run" evidence.
            report["elastic"] = {
                **elastic,
                "scale_up_events": scale_ups,
                "scale_down_events": scale_downs,
                "join_warm_s": join_hist,
            }
        if args.trace_dir:
            # One pulse scrape while every worker is still alive: the
            # merged counters/histograms + Prometheus exposition land as
            # drill artifacts (pulse.json / pulse.prom), and its totals
            # are auditable against the per-worker payloads it carries.
            from distributed_ghs_implementation_tpu.obs.pulse import (
                FleetPulse,
            )

            pulse = FleetPulse(fleet_router, out_dir=args.trace_dir)
            scraped = pulse.scrape_once()
            report["pulse"] = {
                "workers_scraped": scraped["workers_scraped"],
                "counters": scraped["counters"],
                "artifacts": ["pulse.json", "pulse.prom"],
            }
        # run_drill's finally drains the fleet: workers flush in-flight
        # responses + export their per-worker obs JSONL (--obs-dir).
    return report


def _flip_bytes(path: str, rng: np.random.Generator, flips: int = 16) -> None:
    """Seeded in-place byte corruption — the bit-rot simulator. Flips land
    in the file's back half so the zip local headers usually stay parsable
    (the nastier case: ``np.load`` would SUCCEED on garbage if nothing
    checked the bytes first)."""
    with open(path, "r+b") as f:
        data = bytearray(f.read())
        if not data:
            return
        lo = len(data) // 2
        for _ in range(flips):
            i = int(rng.integers(lo, len(data)))
            data[i] ^= 0xFF
        f.seek(0)
        f.write(data)


def run_corrupt_drill(args) -> dict:
    """The corruption audit drill (``gate-verify-v1``): prove the verify
    layer turns every corruption the stack can suffer into a counter, a
    quarantine, or a transparent correction — never a wrong answer.

    Five phases, all seeded and exactly counted:

    A. **Populate** — solve a seeded pool through a verify-enabled
       service with a disk store; record the NetworkX oracle weight per
       digest (the drill's independent ground truth — every response in
       every later phase is checked against it, and ``wrong_results``
       gates EXACTLY at zero).
    B. **Bit rot** — flip seeded bytes inside K live store npz files.
    C. **Restart + re-query** — a fresh service on the same store
       directory re-serves the pool: the K rotted files must fail their
       sha256 sidecars, land in ``.quarantine/`` (``quarantined == K``
       exact), and degrade to misses that re-solve correctly; the
       untouched files must still disk-hit.
    D. **Memory corruption** — mutate the edge ids of M results inside
       the live memory LRU (the bit-flipped-RAM / miscompiled-kernel
       stand-in nothing below the certificate can see). Re-queries must
       fail their inline certificates and serve transparently corrected
       answers (``verify.corrected += M`` exact).
    E. **Payload chaos** (``--payload-chaos N``) — a one-worker TCP fleet
       with the transport chaos layer armed: ``fleet.chaos.payload``
       corrupts N solve responses PAST framing (valid length, valid CRC,
       mutated edge set + weight). The router's response verification
       must reject each one and re-dispatch (``verify.corrected += N``
       exact, ``lost_accepted == 0``).

    Plus an overhead leg: warm-hit latency with sampled async audit vs
    verification off (``verify_overhead_p50_s`` = p50 of the inline
    certificate itself, from the live ``verify.check_s`` histogram).
    """
    import tempfile

    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.obs.events import BUS, quantile
    from distributed_ghs_implementation_tpu.serve.service import MSTService
    from distributed_ghs_implementation_tpu.utils.integrity import (
        list_quarantined,
    )
    from distributed_ghs_implementation_tpu.utils.verify import (
        networkx_mst_weight,
    )

    BUS.enable()
    BUS.clear()
    t_start = time.perf_counter()
    checks: List[dict] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        checks.append({"name": name, "ok": bool(ok), "detail": str(detail)})
        if not ok:
            print(f"CHECK FAIL {name}: {detail}", file=sys.stderr)

    K = args.corrupt_store
    M = args.corrupt_results
    N = args.payload_chaos
    spec = args.verify or "full"
    rng = np.random.default_rng(args.seed)
    store_dir = tempfile.mkdtemp(prefix="ghs-verify-store-")
    pool = [
        gnm_random_graph(120, 360, seed=args.seed + 300 + i)
        for i in range(max(K + 2, 6))
    ]

    def _req(g, cls="bulk", **kw):
        out = _graph_request(g, cls)
        out.update(kw)
        return out

    expected = {}  # digest -> (graph, oracle weight)
    wrong = 0

    def _check_weight(resp, where: str) -> None:
        nonlocal wrong
        digest = resp.get("digest")
        want = expected.get(digest)
        if not resp.get("ok") or want is None or (
            resp.get("total_weight") != want[1]
        ):
            wrong += 1
            print(
                f"WRONG RESULT [{where}]: got "
                f"{resp.get('total_weight')} want "
                f"{None if want is None else want[1]} ({resp.get('error')})",
                file=sys.stderr,
            )

    # -- A: populate ----------------------------------------------------
    svc = MSTService(backend="device", disk_dir=store_dir, verify=spec)
    for g in pool:
        resp = svc.handle(_req(g))
        expected[resp["digest"]] = (g, networkx_mst_weight(g))
        _check_weight(resp, "populate")
        if resp.get("verified") != "full":
            check("populate.verified_full", False, str(resp))
    check("populate.served", wrong == 0, f"wrong={wrong}")

    # -- B: bit rot in live store files ----------------------------------
    npz_files = sorted(
        e.path for e in os.scandir(store_dir)
        if e.name.endswith(".npz")
    )
    check(
        "store.populated", len(npz_files) == len(pool),
        f"{len(npz_files)} files for {len(pool)} digests",
    )
    victims = [npz_files[int(i)] for i in rng.choice(
        len(npz_files), size=min(K, len(npz_files)), replace=False
    )]
    for path in victims:
        _flip_bytes(path, rng)

    # -- C: restart + re-query -------------------------------------------
    pre = dict(BUS.counters())
    svc2 = MSTService(backend="device", disk_dir=store_dir, verify=spec)
    for g in pool:
        _check_weight(svc2.handle(_req(g)), "post-rot")
    delta = {
        k: BUS.counters().get(k, 0) - pre.get(k, 0)
        for k in ("serve.store.quarantined", "serve.store.disk_hit",
                  "serve.scheduler.fresh_solve")
    }
    quarantined_files = list_quarantined(store_dir)
    check(
        "rot.quarantined_exact",
        delta["serve.store.quarantined"] == len(victims)
        and len(quarantined_files) == len(victims),
        f"counter={delta['serve.store.quarantined']} files="
        f"{len(quarantined_files)} expected={len(victims)}",
    )
    check(
        "rot.survivors_disk_hit",
        delta["serve.store.disk_hit"] == len(pool) - len(victims),
        f"disk_hit={delta['serve.store.disk_hit']}",
    )
    check(
        "rot.resolved_fresh",
        delta["serve.scheduler.fresh_solve"] == len(victims),
        f"fresh={delta['serve.scheduler.fresh_solve']}",
    )

    # -- D: memory corruption + transparent correction -------------------
    pre = dict(BUS.counters())
    mem_keys = list(svc2.store._mem)[:M]
    for key in mem_keys:
        result = svc2.store._mem[key]
        if result.num_edges >= 2:
            result.edge_ids[0] = result.edge_ids[1]  # duplicated edge id
    for key in mem_keys:
        digest = key.split(":", 1)[0]
        _check_weight(
            svc2.handle(_req(expected[digest][0])), "mem-corrupt"
        )
    delta = {
        k: BUS.counters().get(k, 0) - pre.get(k, 0)
        for k in ("verify.failed", "verify.corrected")
    }
    check(
        "mem.corrected_exact",
        delta["verify.failed"] == len(mem_keys)
        and delta["verify.corrected"] == len(mem_keys),
        f"failed={delta['verify.failed']} corrected="
        f"{delta['verify.corrected']} expected={len(mem_keys)}",
    )

    # -- E: fleet payload chaos ------------------------------------------
    fleet_section = None
    payload_rejected = 0
    lost_accepted = 0
    if N > 0:
        from distributed_ghs_implementation_tpu.fleet.router import (
            FleetConfig,
            FleetRouter,
        )
        from distributed_ghs_implementation_tpu.utils.resilience import FAULTS

        pre = dict(BUS.counters())
        cfg = FleetConfig(
            workers=1, transport="tcp", chaos=True, chaos_seed=args.seed,
            verify_responses=True, forward_cache=False, verify=spec,
            heartbeat_interval_s=0.25, ready_timeout_s=240.0,
            request_timeout_s=120.0,
        )
        accepted = answered = 0
        with FleetRouter(cfg) as router:
            fleet_pool = pool[: N + 2]
            for i, g in enumerate(fleet_pool):
                if 1 <= i <= N:
                    # Arm ONE shot per corrupted request (mid-run, after
                    # the first clean answer): the first response carrying
                    # an edge set is mutated past framing; the router's
                    # certificate must reject it and the single
                    # re-dispatch must come back clean — arming times=N in
                    # one shot would corrupt the retry too.
                    FAULTS.arm("fleet.chaos.payload", times=1)
                accepted += 1
                resp = router.handle(_req(g, edges_out=True))
                if resp.get("ok"):
                    answered += 1
                _check_weight(resp, "payload-chaos")
        delta = {
            k: BUS.counters().get(k, 0) - pre.get(k, 0)
            for k in ("fleet.chaos.payload_corrupted",
                      "fleet.response.rejected", "verify.failed",
                      "verify.corrected")
        }
        payload_rejected = int(delta["fleet.response.rejected"])
        check(
            "payload.rejected_exact",
            delta["fleet.chaos.payload_corrupted"] == N
            and delta["fleet.response.rejected"] == N
            and delta["verify.corrected"] == N,
            f"{delta} expected {N}",
        )
        lost_accepted = accepted - answered
        check(
            "payload.lost_accepted_zero", lost_accepted == 0,
            f"accepted={accepted} answered={answered}",
        )
        fleet_section = {
            "workers": 1, "transport": "tcp",
            "accepted": accepted, "answered": answered,
            "payload_corrupted": int(delta["fleet.chaos.payload_corrupted"]),
            "response_rejected": payload_rejected,
        }

    # -- overhead leg ----------------------------------------------------
    # Warm-hit latency with the default sampled-audit cadence vs
    # verification off, PACED (~2 ms between arrivals): the claim under
    # test is "sampled audit adds ≤5% to interactive p99 at a realistic
    # request rate", not "an audit thread saturated by a closed loop is
    # free" — at saturation the GIL contention measures the box, not the
    # design. The bound stays generous (1.5x + 5 ms absolute) because a
    # 2-core CI runner's p99 over 120 samples is one scheduler hiccup.
    hit_graph = pool[0]
    svc_off = MSTService(backend="device")
    svc_audit = MSTService(backend="device", verify="sample")
    for s in (svc_off, svc_audit):
        s.handle(_req(hit_graph, cls="interactive"))  # prime the cache
    timings = {}
    for name, s in (("off", svc_off), ("audit", svc_audit)):
        samples = []
        for _ in range(120):
            t0 = time.perf_counter()
            s.handle(_req(hit_graph, cls="interactive"))
            samples.append(time.perf_counter() - t0)
            time.sleep(0.002)
        timings[name] = samples
    svc_audit.verifier.auditor.flush()
    hist = BUS.histograms().get("verify.check_s", {})
    audit_p99 = quantile(timings["audit"], 0.99)
    off_p99 = quantile(timings["off"], 0.99)
    check(
        "audit.p99_overhead_bounded",
        audit_p99 <= max(off_p99 * 1.5, off_p99 + 0.005),
        f"audit p99 {audit_p99:.5f}s vs off {off_p99:.5f}s",
    )

    counters = BUS.counters()
    quarantined_total = int(counters.get("serve.store.quarantined", 0))
    check("wrong_results_zero", wrong == 0, f"wrong={wrong}")
    metrics = {
        "wrong_results": wrong,
        "quarantined": quarantined_total,
        "verify_failed": int(counters.get("verify.failed", 0)),
        "verify_corrected": int(counters.get("verify.corrected", 0)),
        "payload_rejected": payload_rejected,
        "lost_accepted": lost_accepted,
        "verify_checks": int(counters.get("verify.checks", 0)),
        "audit_failed": int(counters.get("verify.audit.failed", 0)),
        "verify_overhead_p50_s": float(hist.get("p50", 0.0)),
        "interactive_hit_audit_p99_s": float(audit_p99),
    }
    ok = all(c["ok"] for c in checks)
    return {
        "schema": REPORT_SCHEMA,
        "config": {
            "workload": WORKLOAD_VERIFY,
            "seed": args.seed,
            "pool": len(pool),
            "corrupt_store": len(victims),
            "corrupt_results": M,
            "payload_chaos": N,
            "verify": spec,
        },
        "wall_s": round(time.perf_counter() - t_start, 3),
        "ok": ok,
        "checks": checks,
        "chaos": {"payload_armed": N, "store_corrupted": len(victims)},
        "events_dropped": BUS.dropped,
        "slo": {"classes": {}},
        "quarantine": quarantined_files,
        "fleet": fleet_section,
        "gate_metrics": {
            "schema": "ghs-bench-metrics-v1",
            "config": {
                "workload": WORKLOAD_VERIFY,
                "seed": args.seed,
                "corrupt_store": len(victims),
                "corrupt_results": M,
                "payload_chaos": N,
            },
            "metrics": metrics,
        },
    }


# ----------------------------------------------------------------------
# Analytics drill (gate-analytics-v1): every query kind, oracle-exact
# ----------------------------------------------------------------------
ANALYTICS_KINDS = ("mst", "components", "k_msf", "bottleneck", "path_max")
ANALYTICS_K = 3  # the deck's k-MSF target fragment count


def _kind_request(g, kind: str, cls: Optional[str]) -> dict:
    """A full solve request for ``kind`` over ``g``. ``cls=None`` drops the
    ``slo_class`` tag so the service applies the kind's own default class
    (``obs.slo.KIND_CLASS_DEFAULTS`` — part of what the drill exercises).
    ``path_max`` endpoints are pinned at ``(0, n-1)``: deterministic, and
    disconnected by construction on the two-block graphs."""
    req = _graph_request(g, cls or "miss")
    if cls is None:
        del req["slo_class"]
    if kind != "mst":
        req["kind"] = kind
    if kind == "components":
        req["labels_out"] = True
    elif kind == "k_msf":
        req["k"] = ANALYTICS_K
    elif kind == "path_max":
        req["u"], req["v"] = 0, g.num_nodes - 1
    return req


def _two_block_graph(seed: int):
    """Deliberately disconnected deck member: two G(n,m) blocks plus three
    isolated tail nodes — the non-mst kinds then see real forests (multi-
    component partitions, the relaxed k-forest spanning predicate, and a
    disconnected ``path_max`` endpoint pair)."""
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )

    a = gnm_random_graph(40, 110, seed=seed)
    b = gnm_random_graph(30, 80, seed=seed + 17)
    return Graph.from_arrays(
        a.num_nodes + b.num_nodes + 3,
        np.concatenate([a.u, b.u + a.num_nodes]),
        np.concatenate([a.v, b.v + a.num_nodes]),
        np.concatenate([a.w, b.w]),
    )


def _kind_oracles(g) -> dict:
    """Per-kind NetworkX ground truth for one graph — every served answer
    in every leg is compared against these, EXACTLY (each oracle answers in
    a tie-independent representation; see analytics/solvers.py)."""
    from distributed_ghs_implementation_tpu.analytics import (
        solvers as asolvers,
    )
    from distributed_ghs_implementation_tpu.utils.verify import (
        networkx_mst_weight,
    )

    parts = asolvers.oracle_components(g)
    return {
        "mst": networkx_mst_weight(g),
        "components": parts,
        "k_eff": min(g.num_nodes, max(ANALYTICS_K, len(parts))),
        "k_msf": asolvers.oracle_k_msf_weight(g, ANALYTICS_K),
        "bottleneck": asolvers.oracle_bottleneck(g),
        "path_max": asolvers.oracle_path_max(g, 0, g.num_nodes - 1),
    }


def run_kinds_drill(args) -> dict:
    """The analytics drill (``gate-analytics-v1``): all five query kinds
    served through the real front door, every answer checked EXACTLY
    against its NetworkX oracle (``wrong_results == 0`` gates per kind —
    a wrong components partition or minimax value is the silent-wrong-MST
    failure mode reborn in a new query class). Five legs:

    A. **Miss** — a seeded pool (connected + deliberately disconnected
       graphs) queried with every kind through a verify-enabled disk-store
       service; per-kind p50 solve latency recorded client-side.
    B. **Hit** — the full deck repeated: every answer must come from cache
       (zero fresh solves, EXACT) and still match its oracle — the
       per-kind keys must hand back the RIGHT kind's entry.
    C. **Probes + store isolation** — ``cached_only`` probes per kind
       (the fleet's forwarding frame): all five hit kind-correctly on a
       fully-queried digest; on an mst-only digest the ``components``
       probe must MISS (per-kind keys never collide, and components never
       derives) while the derivable kinds answer from the mst entry; a
       fresh service on the same directory disk-hits a kind entry; the
       store's npz census is exact (per-kind files per digest).
    D. **Update** — reweight windows through ``op: update``; the digest
       chain is validated against a client-side rebuild, the updated mst
       entry must answer the post-update mst query from cache, and every
       kind is re-checked against fresh oracles of the mutated graph
       (components rides the unchanged connectivity twin's cache — the
       deliberate cross-kind affinity).
    E. **Fleet** — a 2-worker pipe fleet with response verification ON:
       all five kinds answer through the router (``certify_claim``'s
       per-kind adapters certify each payload router-side), plus a repeat
       to prove cross-request affinity inside the fleet.
    """
    import tempfile

    from distributed_ghs_implementation_tpu.analytics import (
        solvers as asolvers,
    )
    from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
    from distributed_ghs_implementation_tpu.graphs.generators import (
        gnm_random_graph,
    )
    from distributed_ghs_implementation_tpu.obs.events import BUS, quantile
    from distributed_ghs_implementation_tpu.serve.service import MSTService

    BUS.enable()
    BUS.clear()
    t_start = time.perf_counter()
    checks: List[dict] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        checks.append({"name": name, "ok": bool(ok), "detail": str(detail)})
        if not ok:
            print(f"CHECK FAIL {name}: {detail}", file=sys.stderr)

    spec = args.verify or "full"
    store_dir = tempfile.mkdtemp(prefix="ghs-analytics-store-")
    pool = [
        gnm_random_graph(90, 260, seed=args.seed + 900 + i)
        for i in range(4)
    ] + [
        _two_block_graph(args.seed + 950),
        _two_block_graph(args.seed + 975),
    ]
    U = 2  # update streams (leg D)

    wrong = {k: 0 for k in ANALYTICS_KINDS}
    served = {k: 0 for k in ANALYTICS_KINDS}

    def check_kind(resp: dict, kind: str, oracles: dict, where: str) -> bool:
        served[kind] += 1
        good = bool(resp.get("ok"))
        if kind == "mst":
            good = good and resp.get("total_weight") == oracles["mst"]
        elif kind == "components":
            got = asolvers.partition_from_labels(resp.get("labels") or [])
            good = good and (
                resp.get("num_components") == len(oracles["components"])
                and got == oracles["components"]
            )
        elif kind == "k_msf":
            good = good and (
                resp.get("total_weight") == oracles["k_msf"]
                and resp.get("num_components") == oracles["k_eff"]
                and resp.get("k") == ANALYTICS_K
            )
        elif kind == "bottleneck":
            good = good and (
                resp.get("bottleneck_weight") == oracles["bottleneck"]
            )
        else:  # path_max — compare the minimax VALUE (the edge can tie)
            pm = oracles["path_max"]
            good = good and (
                resp.get("connected") == pm["connected"]
                and resp.get("path_max_weight") == pm["weight"]
            )
        if not good:
            wrong[kind] += 1
            print(
                f"WRONG RESULT [{where}/{kind}]: "
                f"{json.dumps(resp, default=str)[:400]}",
                file=sys.stderr,
            )
        return good

    # -- A: miss leg — every kind, oracle-checked, latency-sampled -------
    svc = MSTService(backend="device", disk_dir=store_dir, verify=spec)
    lat = {k: [] for k in ANALYTICS_KINDS}
    oracle_of = {}  # digest of pool[i] -> its oracle dict
    for g in pool:
        oracles = _kind_oracles(g)
        oracle_of[g.digest()] = oracles
        for kind in ANALYTICS_KINDS:
            t0 = time.perf_counter()
            resp = svc.handle(
                _kind_request(g, kind, "miss" if kind == "mst" else None)
            )
            lat[kind].append(time.perf_counter() - t0)
            check_kind(resp, kind, oracles, "miss")
            if spec == "full" and resp.get("verified") != "full":
                check("miss.verified_full", False,
                      f"{kind}: verified={resp.get('verified')}")
    check(
        "miss.served_exact",
        all(wrong[k] == 0 for k in ANALYTICS_KINDS),
        f"wrong={wrong}",
    )

    # -- B: hit leg — cached answers, kind-correct, zero fresh solves ----
    pre = dict(BUS.counters())
    uncached = 0
    for g in pool:
        oracles = oracle_of[g.digest()]
        for kind in ANALYTICS_KINDS:
            resp = svc.handle(_kind_request(g, kind, "hit"))
            check_kind(resp, kind, oracles, "hit")
            if not resp.get("cached"):
                uncached += 1
    hit_fresh = int(
        BUS.counters().get("serve.scheduler.fresh_solve", 0)
        - pre.get("serve.scheduler.fresh_solve", 0)
    )
    check("hit.all_cached", uncached == 0, f"uncached={uncached}")
    check("hit.zero_fresh_solves", hit_fresh == 0, f"fresh={hit_fresh}")

    # -- C: kind probes + store isolation --------------------------------
    pre = dict(BUS.counters())
    d0 = pool[0].digest()
    oracles0 = oracle_of[d0]

    def _probe(svc_, digest: str, kind: str, n: int) -> dict:
        req = {"op": "solve", "cached_only": True, "digest": digest}
        if kind != "mst":
            req["kind"] = kind
        if kind == "components":
            req["labels_out"] = True
        elif kind == "k_msf":
            req["k"] = ANALYTICS_K
        elif kind == "path_max":
            req["u"], req["v"] = 0, n - 1
        return svc_.handle(req)

    for kind in ANALYTICS_KINDS:
        resp = _probe(svc, d0, kind, pool[0].num_nodes)
        check_kind(resp, kind, oracles0, "probe")

    # An mst-only digest: the components probe must MISS (per-kind keys
    # never collide with the mst entry, and components never derives —
    # its canonical cache entry is the connectivity forest); the derived
    # kinds answer from the cached mst entry without solving.
    g_extra = gnm_random_graph(70, 200, seed=args.seed + 990)
    oracles_extra = _kind_oracles(g_extra)
    resp = svc.handle(_kind_request(g_extra, "mst", "miss"))
    check_kind(resp, "mst", oracles_extra, "extra")
    d_extra = resp["digest"]
    resp = _probe(svc, d_extra, "components", g_extra.num_nodes)
    check(
        "probe.components_no_collision",
        not resp.get("ok") and resp.get("cache_miss") is True,
        f"components probe on an mst-only digest answered: {resp}",
    )
    for kind in ("k_msf", "bottleneck", "path_max"):
        resp = _probe(svc, d_extra, kind, g_extra.num_nodes)
        check_kind(resp, kind, oracles_extra, "probe-derive")

    delta = {
        k: BUS.counters().get(k, 0) - pre.get(k, 0)
        for k in ("serve.probe.hit", "serve.probe.miss")
    }
    probe_hits = int(delta["serve.probe.hit"])
    probe_misses = int(delta["serve.probe.miss"])
    check(
        "probe.counts_exact",
        probe_hits == 8 and probe_misses == 1,
        f"hits={probe_hits} misses={probe_misses} expected 8/1",
    )

    # A fresh service on the same directory must answer a kind query from
    # the DISK layer (a full request, not a probe: the disk round trip
    # needs the graph to rebuild the result) — with zero fresh solves.
    svc2 = MSTService(backend="device", disk_dir=store_dir, verify=spec)
    pre2 = dict(BUS.counters())
    resp = svc2.handle(_kind_request(pool[0], "components", "hit"))
    check_kind(resp, "components", oracles0, "disk-restart")
    disk_delta = {
        k: BUS.counters().get(k, 0) - pre2.get(k, 0)
        for k in ("serve.store.disk_hit", "serve.scheduler.fresh_solve")
    }
    check(
        "restart.kind_disk_hit",
        disk_delta["serve.store.disk_hit"] == 1
        and disk_delta["serve.scheduler.fresh_solve"] == 0
        and bool(resp.get("cached")),
        f"{disk_delta} cached={resp.get('cached')}",
    )

    # -- D: update leg — digest chain + post-update kind queries ---------
    update_mst_hits = 0
    for si, g in enumerate(pool[:U]):
        rngu = np.random.default_rng(args.seed + 1300 + si)
        idx = rngu.choice(g.num_edges, size=3, replace=False)
        w2 = g.w.copy()
        updates = []
        for j in idx:
            new_w = int(w2[j]) + 7 + si
            w2[j] = new_w
            updates.append({
                "kind": "reweight",
                "u": int(g.u[j]), "v": int(g.v[j]), "w": new_w,
            })
        resp = svc.handle({
            "op": "update", "digest": g.digest(), "updates": updates,
            "slo_class": "update",
        })
        g2 = Graph.from_arrays(g.num_nodes, g.u, g.v, w2)
        check(
            f"update.digest_chain.{si}",
            bool(resp.get("ok")) and resp.get("digest") == g2.digest(),
            f"server {resp.get('digest')} vs client {g2.digest()}",
        )
        oracles2 = _kind_oracles(g2)
        for kind in ANALYTICS_KINDS:
            resp2 = svc.handle(_kind_request(g2, kind, "update"))
            check_kind(resp2, kind, oracles2, f"post-update/{si}")
            if kind == "mst" and resp2.get("cached"):
                update_mst_hits += 1
    check(
        "update.mst_served_from_update_cache",
        update_mst_hits == U,
        f"cached mst answers post-update: {update_mst_hits}/{U}",
    )

    # Store census, EXACT: per pool digest {mst, components kind entry,
    # k_msf kind entry, connectivity-twin mst} = 4 files; the extra graph
    # adds its mst file; each update stream adds {updated mst, components
    # kind, k_msf kind} = 3 — the twin is reweight-invariant (same
    # endpoints, index weights), so its phase-A entry is REUSED, and
    # bottleneck/path_max never store separately. Probe-derived k_msf
    # entries are memory-only by design.
    n_files = sum(
        1 for e in os.scandir(store_dir) if e.name.endswith(".npz")
    )
    expected_files = 4 * len(pool) + 1 + 3 * U
    check(
        "store.per_kind_census_exact",
        n_files == expected_files,
        f"{n_files} npz files, expected {expected_files}",
    )

    # -- E: fleet leg — all kinds through the router, certified ----------
    from distributed_ghs_implementation_tpu.fleet.router import (
        FleetConfig,
        FleetRouter,
    )

    fleet_pool = [
        gnm_random_graph(80, 230, seed=args.seed + 1500),
        _two_block_graph(args.seed + 1600),
    ]
    fleet_oracles = [_kind_oracles(g) for g in fleet_pool]
    cfg = FleetConfig(
        workers=2, verify=spec, verify_responses=True,
        ready_timeout_s=240.0, request_timeout_s=120.0,
    )
    fleet_wrong = fleet_served = 0
    pre = dict(BUS.counters())
    with FleetRouter(cfg) as router:
        for g, oracles in zip(fleet_pool, fleet_oracles):
            for kind in ANALYTICS_KINDS:
                req = _kind_request(g, kind, "fleet")
                req["edges_out"] = True  # router-side claim certification
                resp = router.handle(req)
                fleet_served += 1
                if not check_kind(resp, kind, oracles, "fleet"):
                    fleet_wrong += 1
        # Cross-request affinity inside the fleet: the repeat must still
        # be kind-correct (same digest, same owner, cached kind entry).
        req = _kind_request(fleet_pool[0], "components", "fleet")
        req["edges_out"] = True
        resp = router.handle(req)
        fleet_served += 1
        if not check_kind(resp, "components", fleet_oracles[0], "fleet-rep"):
            fleet_wrong += 1
    fleet_rejected = int(
        BUS.counters().get("fleet.response.rejected", 0)
        - pre.get("fleet.response.rejected", 0)
    )
    check("fleet.kinds_exact", fleet_wrong == 0, f"wrong={fleet_wrong}")
    check(
        "fleet.no_rejected_responses", fleet_rejected == 0,
        f"rejected={fleet_rejected}",
    )

    counters = BUS.counters()
    total_wrong = sum(wrong.values())
    check("wrong_results_zero", total_wrong == 0, f"wrong={wrong}")
    metrics = {
        "wrong_results": total_wrong,
        "hit_leg_fresh_solves": hit_fresh,
        "probe_hits": probe_hits,
        "probe_misses": probe_misses,
        "store_files": n_files,
        "update_streams": U,
        "update_mst_hits": update_mst_hits,
        "fleet_served": fleet_served,
        "fleet_wrong_results": fleet_wrong,
        "verify_failed": int(counters.get("verify.failed", 0)),
        "verify_corrected": int(counters.get("verify.corrected", 0)),
    }
    for k in ANALYTICS_KINDS:
        metrics[f"wrong_{k}"] = wrong[k]
        metrics[f"served_{k}"] = served[k]
        metrics[f"{k}_p50_s"] = float(quantile(lat[k], 0.5))
    ok = all(c["ok"] for c in checks)
    return {
        "schema": REPORT_SCHEMA,
        "config": {
            "workload": WORKLOAD_KINDS,
            "seed": args.seed,
            "pool": len(pool),
            "kinds": list(ANALYTICS_KINDS),
            "k": ANALYTICS_K,
            "update_streams": U,
            "fleet_workers": 2,
            "verify": spec,
        },
        "wall_s": round(time.perf_counter() - t_start, 3),
        "ok": ok,
        "checks": checks,
        "chaos": {},
        "events_dropped": BUS.dropped,
        "slo": {"classes": {}},
        "fleet": {
            "workers": 2, "transport": "pipe",
            "served": fleet_served, "rejected": fleet_rejected,
        },
        "gate_metrics": {
            "schema": "ghs-bench-metrics-v1",
            "config": {
                "workload": WORKLOAD_KINDS,
                "seed": args.seed,
                "pool": len(pool),
                "k": ANALYTICS_K,
                "update_streams": U,
            },
            "metrics": metrics,
        },
    }


def run_gate(report: dict, baseline_path: str, time_tolerance: float):
    """Compare the report's gate metrics against the committed baseline
    (reusing bench_gate's classification); returns ``(ok, lines)``."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import bench_gate

    with open(baseline_path) as f:
        baseline = json.load(f)
    return bench_gate.compare(
        baseline, report["gate_metrics"], time_tolerance=time_tolerance
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="load_drill", description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="the CI deck: ~10s window, mid-flight chaos, gate-ready")
    p.add_argument("--chaos", action="store_true",
                   help="heavier chaos scenario (second mid-flight arm point)")
    p.add_argument("--no-chaos", action="store_true",
                   help="disable the deck's mid-flight fault arming")
    p.add_argument("--arrival", choices=("poisson", "bursty", "ramp"),
                   default="poisson")
    p.add_argument("--ramp", action="store_true",
                   help="shorthand for --arrival ramp (the elastic "
                   "scenario's traffic shape: density doubles across the "
                   "window)")
    p.add_argument("--duration", type=float, default=10.0,
                   help="arrival window in seconds (open-loop)")
    p.add_argument("--rate", type=float, default=10.0,
                   help="average arrivals/sec scale (10 = reference deck)")
    p.add_argument("--seed", type=int, default=23)
    p.add_argument("--lanes", type=int, default=4,
                   help="batch lanes for the service under test")
    p.add_argument("--batch-wait", type=float, default=0.02,
                   help="lane-forming window (s); wider than prod default "
                   "so open-loop bursts actually share lanes")
    p.add_argument("--oversize", type=int, default=2,
                   help="oversize-bypass queries in the deck")
    p.add_argument("--oversize-heavy", action="store_true",
                   help="bulk-vs-interactive scenario (gate-oversize-v1): "
                   "more oversize solves running concurrently with the "
                   "interactive classes; checks interactive p99 stays "
                   "within --interactive-p99-bound while bulk is in flight")
    p.add_argument("--update-heavy", action="store_true",
                   help="streaming scenario (gate-stream-v1): a sustained "
                   "Poisson stream of published update windows against "
                   "subscribed graphs with a durable log, notification "
                   "latency per poll, and (with --fleet --kill-worker) a "
                   "mid-stream kill recovered by snapshot+WAL replay with "
                   "zero fresh solves and no notification gap/duplicate")
    p.add_argument("--streams", type=int, default=3,
                   help="with --update-heavy: subscribed streams in the deck")
    p.add_argument("--sharded-lane", type=int, nargs="?", const=-1, default=0,
                   metavar="N",
                   help="attach a mesh-sharded oversize lane to the service "
                   "under test (bare flag = all devices; with --fleet: "
                   "every worker owns a lane and the router steers "
                   "oversize digests at lane workers)")
    p.add_argument("--interactive-p99-bound", type=float, default=8.0,
                   help="with --oversize-heavy: fail if the hit/dup classes' "
                   "bus-joined p99 exceeds this while bulk solves run")
    p.add_argument("--workers", type=int, default=16,
                   help="client threads (the open-loop dispatch pool)")
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="drive a fleet of N worker processes through the "
                   "digest router instead of the in-process service "
                   "(fleet/router.py, docs/FLEET.md)")
    p.add_argument("--kill-worker", type=int, nargs="?", const=1,
                   default=None, metavar="K",
                   help="with --fleet: arm fleet.worker.crash inside worker "
                   "K mid-window (it dies in place of its next request); "
                   "the drill then asserts zero lost accepted queries, "
                   "re-queue, restart-with-backoff, and goodput recovery")
    p.add_argument("--transport", choices=("pipe", "tcp"), default="pipe",
                   help="with --fleet: the router<->worker channel — "
                   "subprocess pipes (round 12) or localhost TCP sockets "
                   "through fleet/transport.py (dial-in hello "
                   "registration, coalesced pipelined frame writes, "
                   "connection-loss + lease-expiry death detection); the "
                   "report's per-class router_hop_s tracks the overhead "
                   "difference")
    p.add_argument("--test-echo", action="store_true",
                   help="with --fleet: spawn jax-free echo workers (canned "
                   "answers, full transport/failover fidelity) — the CI "
                   "TCP kill drill's mode")
    p.add_argument("--wire", choices=("json", "binary"), default="json",
                   help="solve-request carrier: 'binary' builds the deck "
                   "as B-frame section requests (Graph.to_wire — raw "
                   "little-endian u/v/w, zero-copy ingest) so the drill "
                   "exercises binary ingest and the router's opaque "
                   "passthrough end to end; digests (and so routing, "
                   "caching, and the deck's reproducibility) are "
                   "unchanged (docs/FLEET.md \"Binary wire\")")
    p.add_argument("--wire-legacy-worker", type=int, default=None,
                   metavar="K",
                   help="with --fleet --wire binary: spawn worker K as a "
                   "legacy build (GHS_FLEET_WIRE=0 — its hello carries no "
                   "caps.wire), so the drill proves the mixed-build "
                   "contract: K's ring share degrades to folded JSON per "
                   "connection, siblings stay opaque, zero lost accepted "
                   "queries")
    p.add_argument("--kill-router", action="store_true",
                   help="with --fleet --test-echo --transport tcp: crash "
                   "the ROUTER mid-window with accepted work outstanding "
                   "(workers are externally spawned --listen processes "
                   "that survive it); a successor on the same durable "
                   "journal re-adopts them warm and replays the orphaned "
                   "accepts — lost_accepted == 0 and journal_unanswered "
                   "== 0 gate exactly (gate-fleet-router-v1)")
    p.add_argument("--partition", type=int, nargs="?", const=1,
                   default=None, metavar="K",
                   help="with --fleet --test-echo --transport tcp: "
                   "one-way partition worker K's link mid-window via the "
                   "transport chaos layer (frames dropped, socket OPEN — "
                   "the lease is the only death signal), heal after "
                   "--partition-duration, assert zero loss, exactly one "
                   "answer per query, warm rejoin, and no lease trip on "
                   "the healthy side (gate-fleet-partition-v1)")
    p.add_argument("--partition-duration", type=float, default=3.0,
                   help="with --partition: seconds the link stays dark "
                   "(must exceed the drill's 1.5s partition lease, or "
                   "the fault heals before detection)")
    p.add_argument("--elastic", action="store_true",
                   help="with --fleet: attach the obs-driven autoscaler "
                   "(fleet/autoscaler.py) with a zero wait budget, so the "
                   "window deterministically grows the pool to "
                   "--elastic-max (warm-handoff joins) and post-window "
                   "idle drains it to --elastic-min (drain-aware "
                   "retires); scale event counts then gate EXACTLY "
                   "(gate-fleet-elastic-v1, docs/FLEET.md Elasticity)")
    p.add_argument("--elastic-min", type=int, default=None, metavar="N",
                   help="with --elastic: pool floor (default fleet - 1, "
                   "at least 1)")
    p.add_argument("--elastic-max", type=int, default=None, metavar="N",
                   help="with --elastic: pool ceiling (default fleet + 1)")
    p.add_argument("--kinds-mixed", action="store_true",
                   help="run the analytics drill (gate-analytics-v1): all "
                   "five query kinds (mst, components, k_msf, bottleneck, "
                   "path_max) over miss/hit/probe/update traffic plus a "
                   "2-worker fleet leg with response certification, every "
                   "answer checked EXACTLY against its NetworkX oracle "
                   "and the per-kind store keys proven non-colliding "
                   "(docs/ANALYTICS.md)")
    p.add_argument("--corrupt-store", type=int, default=None, metavar="K",
                   help="run the corruption audit drill (gate-verify-v1): "
                   "flip seeded bytes inside K live store npz files "
                   "mid-run, corrupt --corrupt-results cached results "
                   "in memory, and arm --payload-chaos response "
                   "corruptions over a TCP fleet; gates wrong_results==0 "
                   "and quarantined/verify.corrected EXACT "
                   "(docs/VERIFICATION.md)")
    p.add_argument("--corrupt-results", type=int, default=2, metavar="M",
                   help="with --corrupt-store: in-memory cached results "
                   "to corrupt (inline certificates must correct each)")
    p.add_argument("--payload-chaos", type=int, default=2, metavar="N",
                   help="with --corrupt-store: fleet.chaos.payload shots "
                   "armed against the one-worker TCP fleet leg (0 skips "
                   "the fleet leg)")
    p.add_argument("--verify", default=None, metavar="SPEC",
                   help="verification policy for the service under test "
                   "(off|sample[:N]|full or per-class — "
                   "docs/VERIFICATION.md); the corrupt drill defaults "
                   "to 'full'")
    p.add_argument("--trace-dir",
                   help="with --fleet: distributed-tracing artifact dir — "
                   "per-process JSONL span logs (workers on drain, router "
                   "post-shutdown), one merged Perfetto trace "
                   "(merged_trace.json) + critical-path report "
                   "(critical_path.json), and a fleet pulse scrape "
                   "(pulse.json / pulse.prom); orphan_spans and "
                   "traces_joined join the gated metrics")
    p.add_argument("--obs-dir",
                   help="with --fleet: per-worker obs JSONL exports land "
                   "here on drain (worker<K>.<incarnation>.jsonl)")
    p.add_argument("--p99-bound", type=float, default=30.0,
                   help="degraded-but-BOUNDED: fail if total p99 exceeds this")
    p.add_argument("--jsonl", help="also export the window's bus events")
    p.add_argument("--output", help="write the JSON report here")
    p.add_argument("--gate-baseline", nargs="?", const=DEFAULT_BASELINE,
                   help="gate the report against this baseline "
                   f"(default {DEFAULT_BASELINE})")
    p.add_argument("--time-tolerance", type=float, default=0.5,
                   help="gate wall-time tolerance (CI uses 5.0)")
    p.add_argument("--update-baseline", nargs="?", const=DEFAULT_BASELINE,
                   help="write the gate baseline from this run and exit")
    args = p.parse_args(argv)
    if args.ramp:
        args.arrival = "ramp"
    global _WIRE_BINARY
    _WIRE_BINARY = args.wire == "binary"
    if args.wire_legacy_worker is not None:
        if args.wire != "binary":
            p.error("--wire-legacy-worker needs --wire binary")
        if not args.fleet or not 0 <= args.wire_legacy_worker < args.fleet:
            p.error("--wire-legacy-worker K needs --fleet N with "
                    "0 <= K < N")
    if args.kill_worker is not None and (
        not args.fleet or not 0 <= args.kill_worker < args.fleet
    ):
        p.error("--kill-worker needs --fleet N with 0 <= K < N")
    if args.trace_dir and not args.fleet:
        p.error("--trace-dir needs --fleet N (it assembles a multi-process "
                "trace; single-process runs have --jsonl)")
    if args.elastic and not args.fleet:
        p.error("--elastic needs --fleet N (it drives the fleet's pool)")
    if args.elastic and not args.obs_dir:
        # Retired workers' counters are recovered from their obs exports;
        # without the export directory the exact-gated counter checks
        # (fresh solves, chain evictions) would lose the retirees' window
        # activity and pass vacuously.
        p.error("--elastic needs --obs-dir (retired workers' counters "
                "are recovered from their obs exports)")
    if args.elastic:
        mn = (args.elastic_min if args.elastic_min is not None
              else max(1, args.fleet - 1))
        mx = (args.elastic_max if args.elastic_max is not None
              else args.fleet + 1)
        if not 1 <= mn <= args.fleet <= mx:
            p.error(f"--elastic needs 1 <= min ({mn}) <= --fleet "
                    f"({args.fleet}) <= max ({mx})")
    if args.test_echo and not args.fleet:
        p.error("--test-echo needs --fleet N (it is a worker mode)")
    if args.kill_router or args.partition is not None:
        if not args.fleet or not args.test_echo or args.transport != "tcp":
            p.error("--kill-router/--partition need --fleet N --test-echo "
                    "--transport tcp (externally spawned --listen echo "
                    "workers are the topology that survives the fault)")
        if args.kill_router and args.partition is not None:
            p.error("--kill-router and --partition are separate scenarios")
        if args.kill_worker is not None or args.elastic:
            p.error("--kill-router/--partition do not compose with "
                    "--kill-worker/--elastic")
    if args.partition is not None and not 0 <= args.partition < args.fleet:
        p.error("--partition K needs 0 <= K < --fleet")
    if args.test_echo and args.update_heavy:
        p.error("--test-echo cannot run --update-heavy (echo workers have "
                "no stream layer)")
    if args.corrupt_store is not None:
        if args.corrupt_store < 1:
            p.error("--corrupt-store K needs K >= 1")
        if args.fleet or args.kill_router or args.partition is not None:
            p.error("--corrupt-store is its own scenario (it spins its "
                    "own one-worker fleet leg via --payload-chaos)")
    if args.kinds_mixed:
        if (args.fleet or args.corrupt_store is not None or args.kill_router
                or args.partition is not None or args.test_echo
                or args.elastic or args.update_heavy or args.oversize_heavy):
            p.error("--kinds-mixed is its own scenario (it spins its own "
                    "2-worker fleet leg internally)")
        # The bare-flag baseline default points at the load baseline;
        # retarget it at the analytics one for this workload.
        if args.gate_baseline == DEFAULT_BASELINE:
            args.gate_baseline = ANALYTICS_BASELINE
        if args.update_baseline == DEFAULT_BASELINE:
            args.update_baseline = ANALYTICS_BASELINE

    report = (
        run_kinds_drill(args) if args.kinds_mixed
        else run_corrupt_drill(args) if args.corrupt_store is not None
        else run_drill(args)
    )
    if args.output:
        with open(args.output, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")

    brief = {
        k: report[k]
        for k in ("schema", "config", "wall_s", "checks", "ok",
                  "events_dropped", "chaos")
    }
    brief["classes"] = {
        cls: {
            "sent": c["sent"],
            "goodput_per_sec": round(c["goodput_per_sec"] or 0, 2),
            "p50_s": round(c["latency_s"].get("p50", 0), 4),
            "p95_s": round(c["latency_s"].get("p95", 0), 4),
            "p99_s": round(c["latency_s"].get("p99", 0), 4),
        }
        for cls, c in report["slo"]["classes"].items()
    }
    print(json.dumps(brief, indent=2))

    if args.update_baseline:
        with open(args.update_baseline, "w") as f:
            json.dump(report["gate_metrics"], f, indent=2)
            f.write("\n")
        print(f"load baseline written: {args.update_baseline}")
        return 0 if report["ok"] else 1

    gate_ok = True
    if args.gate_baseline:
        gate_ok, lines = run_gate(
            report, args.gate_baseline, args.time_tolerance
        )
        for line in lines:
            print(line)
        workload = report["config"]["workload"]
        print(f"load gate ({workload}): {'PASS' if gate_ok else 'FAIL'}")

    print(f"load drill: {'PASS' if report['ok'] and gate_ok else 'FAIL'}")
    return 0 if report["ok"] and gate_ok else 1


if __name__ == "__main__":
    sys.exit(main())
