"""Batched Borůvka/GHS MST solver — the flagship model.

The whole GHS protocol (``/root/reference/ghs_implementation.py:118-413``)
runs here as one on-device loop. One *level* (the reference's round shape,
SURVEY.md §3.4) is:

  1. candidate filter — intra-fragment edges die (TEST -> REJECT analog),
  2. ``fragment_moe`` — per-fragment minimum outgoing edge via one rank-keyed
     segment minimum (TEST/ACCEPT + REPORT convergecast analog),
  3. ``hook_and_compress`` — symmetric-hook resolution + pointer jumping
     (CONNECT/INITIATE/CHANGEROOT analog),
  4. winning ranks are recorded as MST edges (BRANCH marking analog,
     ``ghs_implementation.py:130-131``).

Levels iterate in a ``lax.while_loop`` until no fragment has an outgoing edge
— the multi-component-safe analog of root termination on ``best_weight ==
inf`` (``ghs_implementation.py:316-320``). At most ``ceil(log2 n)`` levels run
because every active fragment merges each level. Unlike the reference's
thread/MPI races (wrong MSTs at 20+ vertices, SURVEY.md preamble), every step
is deterministic: same graph in, identical MST out.

Edges are compared by precomputed int32 *rank* (host-side sort by ``(weight,
edge id)`` — ``Graph.rank_arrays``), so weights never reach the device and a
level costs two e-sized gathers, one e-sized select, and one segment_min.
"""

from __future__ import annotations

import functools
import math
import time
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.ops.segment_ops import INT32_MAX, fragment_moe
from distributed_ghs_implementation_tpu.ops.union_find import hook_and_compress


class BoruvkaState(NamedTuple):
    """Carried through the level loop (the analog of all per-node protocol
    state — ``NodeState``/``level``/``fragment_id``/``best_edge`` at
    ``ghs_implementation.py:55-66`` — flattened into three arrays)."""

    fragment: jax.Array  # [n] int32: fragment (root) id per vertex
    mst_ranks: jax.Array  # [m] bool: edge ranks chosen for the MST
    level: jax.Array  # scalar int32: levels completed
    progress: jax.Array  # scalar bool: did the last level merge anything


def boruvka_level(
    state: BoruvkaState,
    src: jax.Array,
    dst: jax.Array,
    rank: jax.Array,
    ra: jax.Array,
    rb: jax.Array,
    *,
    axis_name: str | None = None,
    identity_fragment: bool = False,
    kernel: str = "xla",
) -> BoruvkaState:
    """One GHS/Borůvka level over (optionally sharded) directed edge slots.

    ``kernel`` selects the fused Pallas forms of the MOE gather/select and
    the hook+compress round (``ops/pallas_kernels.py``) — a static
    trace-time choice with identical results either way.
    """
    fragment = state.fragment
    has_moe, moe_rank, moe_dst_frag = fragment_moe(
        fragment, src, dst, rank, ra, rb,
        axis_name=axis_name, identity_fragment=identity_fragment,
        kernel=kernel,
    )
    new_fragment, _ = hook_and_compress(
        has_moe, moe_dst_frag, fragment, kernel=kernel
    )

    # Record winning ranks. Sharded: each shard owns a contiguous rank block
    # and marks only winners inside it.
    if axis_name is None:
        safe = jnp.where(has_moe, moe_rank, 0)
        mst_ranks = state.mst_ranks.at[safe].max(has_moe)
    else:
        m_local = state.mst_ranks.shape[0]
        shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
        local = moe_rank - shard * m_local
        mine = has_moe & (local >= 0) & (local < m_local)
        safe = jnp.where(mine, local, 0)
        mst_ranks = state.mst_ranks.at[safe].max(mine)

    return BoruvkaState(
        fragment=new_fragment,
        mst_ranks=mst_ranks,
        level=state.level + 1,
        progress=jnp.any(has_moe),
    )


def _max_levels(num_nodes: int) -> int:
    return max(1, math.ceil(math.log2(max(num_nodes, 2)))) + 1


# Measured crossover: below this edge count the flat kernel's shared shape
# buckets beat ELL's per-degree-signature compiles (single-device and sharded
# auto strategies both use it).
ELL_AUTO_EDGE_THRESHOLD = 1 << 17


def boruvka_solve(
    fragment0: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    rank: jax.Array,
    ra: jax.Array,
    rb: jax.Array,
    *,
    kernel: str = "xla",
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full single-device solve from an arbitrary starting partition.

    Correct for any ``fragment0`` whose entries are root ids (vertices may be
    pre-merged — e.g. resuming from a checkpoint). Returns
    ``(mst_ranks[m], fragment[n], levels)``. Jit-friendly: fixed shapes,
    on-device ``while_loop``, no host sync inside.
    """
    n = fragment0.shape[0]
    state = BoruvkaState(
        fragment=fragment0,
        mst_ranks=jnp.zeros(ra.shape[0], dtype=bool),
        level=jnp.zeros((), jnp.int32),
        progress=jnp.ones((), bool),
    )
    max_levels = _max_levels(n)

    def cond(s: BoruvkaState):
        return s.progress & (s.level < max_levels)

    def body(s: BoruvkaState):
        return boruvka_level(s, src, dst, rank, ra, rb, kernel=kernel)

    final = jax.lax.while_loop(cond, body, state)
    return final.mst_ranks, final.fragment, final.level


@functools.partial(jax.jit, static_argnames=("num_nodes", "kernel"))
def _solve_from_iota(src, dst, rank, ra, rb, *, num_nodes: int, kernel: str = "xla"):
    """Solve from the identity partition, with the level-0 fast path (the
    relabel gathers on the biggest level are skipped because fragment == iota;
    only safe when the partition really is the identity)."""
    state = BoruvkaState(
        fragment=jnp.arange(num_nodes, dtype=jnp.int32),
        mst_ranks=jnp.zeros(ra.shape[0], dtype=bool),
        level=jnp.zeros((), jnp.int32),
        progress=jnp.ones((), bool),
    )
    max_levels = _max_levels(num_nodes)
    state = boruvka_level(
        state, src, dst, rank, ra, rb, identity_fragment=True, kernel=kernel
    )

    def cond(s: BoruvkaState):
        return s.progress & (s.level < max_levels)

    def body(s: BoruvkaState):
        return boruvka_level(s, src, dst, rank, ra, rb, kernel=kernel)

    final = jax.lax.while_loop(cond, body, state)
    return final.mst_ranks, final.fragment, final.level


_jit_solve = jax.jit(boruvka_solve, static_argnames=("kernel",))


# ---------------------------------------------------------------------------
# ELL (degree-bucketed) kernel — the fast path on TPU.
#
# The flat kernel's per-level cost is dominated by the e-sized scatter inside
# segment_min (~8 ns/element on v5e). The ELL layout (Graph.ell_buckets)
# makes the per-vertex MOE a dense row-min over static 2-D blocks, so the only
# scatters left are n-sized: measured ~2x end-to-end over the flat kernel on
# RMAT-18/20. Stage 2 (per-fragment min over per-vertex minima) is the
# reference's REPORT convergecast collapsed to one n-sized scatter-min.
# ---------------------------------------------------------------------------


def _ell_level(
    fragment, mst_ranks, buckets, ra, rb, *, axis_name=None,
    identity_fragment=False, kernel="xla",
):
    """One level over ELL buckets; returns (fragment2, mst2, has_any).

    ``kernel="pallas"`` runs each bucket's scan through the fused
    VMEM-resident row-min kernel (``pallas_kernels.fused_ell_row_min`` —
    both fragment gathers + mask + row reduction in one pass) and the
    merge through the fused hook+compress kernel; guarded geometries and
    ``"xla"`` take the plain forms below. Identical results either way.

    With ``axis_name``, bucket rows are a shard and per-vertex minima are
    merged across the mesh with one ``lax.pmin`` — the single collective per
    level in the vertex-sharded layout. ``identity_fragment`` marks the
    level-0 fast path: when ``fragment == iota`` the two bucket gathers are
    the identity, and because rows hold no self-edges *every* row entry is
    outgoing — the whole scan collapses to "first rank in each row" (rows are
    rank-sorted), skipping the level's dominant cost (the ~2e-sized
    ``fragment[dstb]`` random gather).
    """
    n = fragment.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    vmin = jnp.full(n, INT32_MAX, jnp.int32)
    if kernel == "pallas":
        from distributed_ghs_implementation_tpu.ops import pallas_kernels as pk
    for verts, dstb, rankb in buckets:
        if identity_fragment:
            row_min = rankb[:, 0]  # rank-sorted rows: first entry is the min
        elif kernel == "pallas" and pk.ell_shape_ok(n, *dstb.shape):
            row_min = pk.fused_ell_row_min(fragment, verts, dstb, rankb)
        else:
            fv = fragment[verts]
            fd = fragment[dstb]
            key = jnp.where(fd != fv[:, None], rankb, INT32_MAX)
            row_min = jnp.min(key, axis=1)
        # Pad rows alias vertex 0 with sentinel minima; scatter-min is inert.
        vmin = vmin.at[verts].min(row_min)
    if axis_name is not None:
        vmin = jax.lax.pmin(vmin, axis_name)
    if identity_fragment:
        moe = vmin  # per-vertex minima ARE per-fragment minima at level 0
    else:
        moe = jnp.full(n, INT32_MAX, jnp.int32).at[fragment].min(vmin)
    has = moe < INT32_MAX
    safe = jnp.where(has, moe, 0)
    if identity_fragment:
        fa = ra[safe]
        fb = rb[safe]
    else:
        fa = fragment[ra[safe]]
        fb = fragment[rb[safe]]
    dst_frag = jnp.where(has, jnp.where(fa == ids, fb, fa), ids)
    fragment2, _ = hook_and_compress(has, dst_frag, fragment, kernel=kernel)
    mst2 = mst_ranks.at[safe].max(has)
    return fragment2, mst2, jnp.any(has)


def ell_solve_loop(buckets, ra, rb, *, num_nodes: int, axis_name=None,
                   kernel="xla"):
    """Full ELL solve from the identity partition (shared by the single-device
    jit wrapper and the sharded shard_map body)."""
    fragment = jnp.arange(num_nodes, dtype=jnp.int32)
    mst_ranks = jnp.zeros(ra.shape[0], dtype=bool)
    fragment, mst_ranks, has = _ell_level(
        fragment, mst_ranks, buckets, ra, rb, axis_name=axis_name,
        identity_fragment=True, kernel=kernel,
    )
    max_levels = _max_levels(num_nodes)

    def cond(s):
        return s[2] & (s[3] < max_levels)

    def body(s):
        f, m, _, lv = s
        f2, m2, h = _ell_level(
            f, m, buckets, ra, rb, axis_name=axis_name, kernel=kernel
        )
        return (f2, m2, h, lv + 1)

    f, m, _, lv = jax.lax.while_loop(
        cond, body, (fragment, mst_ranks, has, jnp.ones((), jnp.int32))
    )
    return m, f, lv


@functools.partial(jax.jit, static_argnames=("num_nodes", "kernel"))
def _solve_ell(buckets, ra, rb, *, num_nodes: int, kernel: str = "xla"):
    return ell_solve_loop(buckets, ra, rb, num_nodes=num_nodes, kernel=kernel)


def prepare_ell_arrays(graph: Graph):
    """Device staging for the ELL kernel: ``(buckets, ra, rb, n_pad)``."""
    n_pad = _next_pow2(graph.num_nodes)
    m_pad = _next_pow2(graph.num_edges)
    ra, rb = graph.rank_endpoints(pad_to=m_pad)
    buckets = tuple(
        (jnp.asarray(verts), jnp.asarray(dstb), jnp.asarray(rankb))
        for verts, dstb, rankb in graph.ell_buckets
    )
    return buckets, jnp.asarray(ra), jnp.asarray(rb), n_pad


# ---------------------------------------------------------------------------
# Host-stepped variant with level-wise edge compaction.
#
# On real graphs most edges become intra-fragment after the first level; the
# on-device while_loop keeps paying full-size gathers regardless. The
# host-stepped path relabels src/dst to fragment ids each level (so the next
# level's "gather fragment of endpoint" is the relabel itself), counts
# surviving edges, and compacts the slot arrays into the next power-of-two
# bucket when they shrink >= 2x. Each bucket size compiles once (cached).
# Cost: one tiny host sync per level — worth it for the 8-64x shrink levels.
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("kernel",))
def _level_kernel(fragment, mst_ranks, src_f, dst_f, rank, ra, rb, *,
                  kernel: str = "xla"):
    """One level over fragment-relabeled slots; returns relabeled survivors.

    ``src_f/dst_f`` hold *current fragment ids* (relabeled each level), so the
    MOE search takes the identity fast path; ``fragment`` still maps original
    vertices (for the rank-indexed far-side lookup and the final result).
    """
    has, moe_rank, dst_frag = fragment_moe(
        fragment, src_f, dst_f, rank, ra, rb, identity_fragment=True,
        kernel=kernel,
    )
    fragment2, parent = hook_and_compress(has, dst_frag, fragment, kernel=kernel)
    safe = jnp.where(has, moe_rank, 0)
    mst2 = mst_ranks.at[safe].max(has)
    src2 = parent[src_f]
    dst2 = parent[dst_f]
    count2 = jnp.sum((src2 != dst2).astype(jnp.int32))
    return fragment2, mst2, src2, dst2, jnp.any(has), count2


@functools.partial(jax.jit, static_argnums=(3,))
def _compact_kernel(src_f, dst_f, rank, out_size: int):
    """Stream-compact alive slots into an ``out_size`` buffer (pads inert)."""
    alive = src_f != dst_f
    pos = jnp.cumsum(alive.astype(jnp.int32)) - 1
    idx = jnp.where(alive, pos, out_size)  # dead slots scatter out of bounds
    new_src = jnp.zeros(out_size, jnp.int32).at[idx].set(src_f, mode="drop")
    new_dst = jnp.zeros(out_size, jnp.int32).at[idx].set(dst_f, mode="drop")
    new_rank = jnp.full(out_size, INT32_MAX, jnp.int32).at[idx].set(rank, mode="drop")
    return new_src, new_dst, new_rank


_COMPACT_MIN_SLOTS = 2048


@functools.partial(jax.jit, static_argnames=("kernel",))
def _continue_solve(fragment, mst_ranks, level, src_f, dst_f, rank, ra, rb, *,
                    kernel: str = "xla"):
    """Finish the solve on-device from a mid-run state (post-compaction)."""
    n = fragment.shape[0]
    state = BoruvkaState(
        fragment=fragment,
        mst_ranks=mst_ranks,
        level=level,
        progress=jnp.ones((), bool),
    )
    max_levels = _max_levels(n)

    def cond(s: BoruvkaState):
        return s.progress & (s.level < max_levels)

    def body(s: BoruvkaState):
        return boruvka_level(s, src_f, dst_f, rank, ra, rb, kernel=kernel)

    final = jax.lax.while_loop(cond, body, state)
    return final.mst_ranks, final.fragment, final.level


def solve_arrays_stepped(
    fragment0,
    src,
    dst,
    rank,
    ra,
    rb,
    *,
    compact: bool = True,
    stepped_levels: int | None = 2,
    initial_state: tuple | None = None,
    on_level=None,
    kernel: str | None = None,
):
    """Host-stepped solve — the single driver behind the hybrid strategy,
    instrumentation, and checkpointing (each was once its own loop copy).

    Runs ``stepped_levels`` levels host-side with edge compaction (one tiny
    sync each), then finishes in the fused on-device while_loop; pass
    ``stepped_levels=None`` to step every level (required when ``on_level``
    must observe each one). ``initial_state`` is ``(fragment, mst_ranks,
    level)`` to resume mid-solve (slots are relabeled to the restored
    partition first). ``on_level(level, fragment, mst_ranks, has_np, count_np,
    wall_time_s)`` fires after each stepped level. ``kernel`` selects the
    fused Pallas level kernels (``None`` = process default via
    ``pallas_kernels.kernel_choice``). Returns
    ``(mst_ranks, fragment, levels)``.
    """
    from distributed_ghs_implementation_tpu.ops.pallas_kernels import (
        kernel_choice,
    )

    kernel = kernel_choice(kernel)
    n = fragment0.shape[0]
    if initial_state is not None:
        fragment, mst_ranks, levels = initial_state
        fragment = jnp.asarray(fragment)
        mst_ranks = jnp.asarray(mst_ranks)
        src_f = fragment[src]
        dst_f = fragment[dst]
    else:
        fragment = fragment0
        mst_ranks = jnp.zeros(ra.shape[0], dtype=bool)
        src_f, dst_f = src, dst  # fragment ids == vertex ids at level 0
        levels = 0
    max_levels = _max_levels(n)
    step_until = max_levels if stepped_levels is None else min(
        levels + stepped_levels, max_levels
    )
    while levels < step_until:
        t0 = time.perf_counter()
        fragment, mst_ranks, src_f, dst_f, has, count = _level_kernel(
            fragment, mst_ranks, src_f, dst_f, rank, ra, rb, kernel=kernel
        )
        levels += 1
        has_np, count_np = jax.device_get((has, count))  # one round trip
        count_np = int(count_np)
        BUS.complete(
            "solver.level",
            time.perf_counter() - t0,
            cat="solver",
            level=levels,
            edges_alive=count_np // 2,  # directed slots -> undirected edges
        )
        if on_level is not None:
            on_level(
                levels, fragment, mst_ranks, bool(has_np), count_np,
                time.perf_counter() - t0,
            )
        if not bool(has_np):
            return mst_ranks, fragment, levels
        if compact:
            cur = src_f.shape[0]
            tgt = max(_next_pow2(count_np), _COMPACT_MIN_SLOTS)
            if 2 * tgt <= cur:
                src_f, dst_f, rank = _compact_kernel(src_f, dst_f, rank, tgt)
    if levels >= max_levels:
        return mst_ranks, fragment, levels
    with BUS.span("solver.fused_finish", cat="solver", from_level=levels):
        mst_ranks, fragment, level = _continue_solve(
            fragment, mst_ranks, jnp.asarray(levels, jnp.int32),
            src_f, dst_f, rank, ra, rb, kernel=kernel,
        )
        level = int(level)
    return mst_ranks, fragment, level


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


def _bucket_size(x: int) -> int:
    """Next size in {1, 1.25, 1.5, 1.75} * 2^k >= x.

    Pure pow2 padding wastes up to 2x on every edge-sized op (a 34.4M-edge
    road graph pads to 67M slots); quarter steps cap the waste at 25% for 4x
    the compiled-shape diversity — cheap now that compilations persist in the
    on-disk XLA cache.
    """
    if x <= 4:
        return max(1, x)
    p = 1 << (x - 1).bit_length()  # pow2 >= x
    for num in (5, 6, 7):  # 1.25, 1.5, 1.75 times p/2
        cand = num * (p >> 3)
        if cand >= x:
            return cand
    return p


def prepare_device_arrays(graph: Graph, *, bucket_shapes: bool = True):
    """Host->device staging: ``(fragment0, src, dst, rank, ra, rb)`` jnp arrays.

    With ``bucket_shapes``, slots/ranks/vertices pad to powers of two so
    same-bucket graphs share one compiled kernel (padding vertices are
    isolated self-fragments; padding slots/ranks are inert).
    """
    n = graph.num_nodes
    n_pad = _next_pow2(n) if bucket_shapes else n
    e_pad = _next_pow2(2 * graph.num_edges) if bucket_shapes else None
    m_pad = e_pad // 2 if e_pad is not None else None
    src, dst, rank, ra, rb = graph.rank_arrays(pad_edges_to=e_pad, pad_ranks_to=m_pad)
    return (
        jnp.arange(n_pad, dtype=jnp.int32),
        jnp.asarray(src),
        jnp.asarray(dst),
        jnp.asarray(rank),
        jnp.asarray(ra),
        jnp.asarray(rb),
    )


def solve_graph(
    graph: Graph,
    *,
    bucket_shapes: bool = True,
    strategy: str = "auto",
    kernel: str | None = None,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host entry: run the solver on a ``Graph``.

    Returns ``(mst_edge_ids, fragment, levels)`` where ``mst_edge_ids`` are
    indices into ``graph.u/v/w`` (undirected), sorted ascending.

    ``strategy``: ``"rank"`` = rank-space solver (default at scale: host-side
    level 1, rank-space level 2, compacted finish — see
    ``models/rank_solver.py``); ``"ell"`` = degree-bucketed dense-reduction
    kernel; ``"fused"`` = flat single on-device while_loop (default for small
    graphs: shared pow2-bucketed compiles, one dispatch); ``"stepped"`` =
    host-stepped levels with edge compaction, kept for instrumentation and
    checkpointing.

    ``kernel`` (``None`` = process default, ``"pallas"``/``"xla"``) selects
    the fused Pallas level kernels on the ``ell``/``fused``/``stepped``
    strategies (docs/KERNELS.md); the rank solver keeps its own XLA-shaped
    program. A Pallas failure at compile or dispatch trips the sticky
    process-wide fallback (``pallas_kernels.disable_pallas``) and the solve
    re-runs on the XLA path — degraded throughput, never a failed solve.
    """
    from distributed_ghs_implementation_tpu.ops import pallas_kernels as pk

    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0
    if strategy == "auto":
        # Rank solver wins at scale (measured ~2.4x over ELL on RMAT-20 and
        # far cheaper host prep); small graphs stay on the shape-bucketed flat
        # kernel (shared compiles, single dispatch).
        strategy = "rank" if graph.num_edges >= ELL_AUTO_EDGE_THRESHOLD else "fused"
    kernel = pk.kernel_choice(kernel)
    with BUS.span(
        "solver.solve", cat="solver",
        strategy=strategy, nodes=n, edges=graph.num_edges, kernel=kernel,
    ):
        if strategy == "rank":
            from distributed_ghs_implementation_tpu.models.rank_solver import (
                solve_graph_rank,
            )

            return solve_graph_rank(graph)
        try:
            if strategy == "ell":
                buckets, ra, rb, n_pad = prepare_ell_arrays(graph)
                mst_ranks, fragment, levels = _solve_ell(
                    buckets, ra, rb, num_nodes=n_pad, kernel=kernel
                )
            elif strategy == "stepped":
                args = prepare_device_arrays(graph, bucket_shapes=bucket_shapes)
                mst_ranks, fragment, levels = solve_arrays_stepped(
                    *args, kernel=kernel
                )
            elif strategy == "fused":
                args = prepare_device_arrays(graph, bucket_shapes=bucket_shapes)
                mst_ranks, fragment, levels = _solve_from_iota(
                    *args[1:], num_nodes=args[0].shape[0], kernel=kernel
                )
            else:
                raise ValueError(f"unknown strategy {strategy!r}")
            # Fetch INSIDE the try: dispatch is async, so a Pallas program
            # that faults at execution raises at this first host sync, not
            # at the solver call above — without this, a runtime failure
            # would escape the fallback below.
            mst_ranks, fragment, levels = jax.device_get(
                (mst_ranks, fragment, levels)
            )
        except ValueError:
            raise
        except Exception as ex:  # noqa: BLE001 — speculative-kernel fallback
            if kernel != "pallas":
                raise
            # The round-5 fallback discipline: a Pallas compile/dispatch
            # failure permanently degrades this process to XLA and the
            # solve re-runs — results stay exact, only throughput changes.
            pk.disable_pallas(f"solve_graph[{strategy}]: {type(ex).__name__}: {ex}")
            return solve_graph(
                graph, bucket_shapes=bucket_shapes, strategy=strategy,
                kernel="xla",
            )
        ranks = np.nonzero(np.asarray(mst_ranks))[0]
        edge_ids = np.sort(graph.edge_id_of_rank(ranks))
        return edge_ids, np.asarray(fragment)[:n], int(levels)
