"""Kernel-vs-XLA parity suite for ``ops/pallas_kernels.py`` (round 15).

Promoted from the standalone hardware probe ``tools/test_pallas_gather.py``:
off-TPU the fused kernels run in Pallas interpret mode — lowered to the
same XLA ops the kernels trace, bit-exact — so edge-for-edge MST parity
between ``kernel="pallas"`` and ``kernel="xla"`` is assertable in CPU-only
tier-1 CI, with no hardware in the loop. The suite covers:

* unit parity of each fused kernel against its two-step XLA form
  (``fused_ell_row_min``, ``fused_gather_key``, ``fused_hook_compress``);
* edge-for-edge MST equality on seeded RMAT (scales 12-14 tier-1, 16-18
  behind the ``slow`` marker) and adversarial fuzz graphs, across every
  strategy that threads the selector;
* the rank-sharded 8-device dryrun path;
* selection semantics: ``GHS_KERNEL``, ``set_default_kernel``, per-solve
  override, auto-fallback off TPU, shape guards, and the sticky
  ``disable_pallas`` runtime fallback (requests never fail, they degrade);
* the lane cache / ``compile.*`` taxonomy keying kernel variants.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_ghs_implementation_tpu.batch.lanes import (
    _SOLVER_CACHE,
    clear_solver_cache,
    compiled_bucket_keys,
    execute_stacked,
    solve_lanes,
    stack_lanes,
)
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    gnm_random_graph,
    rmat_graph,
)
from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.ops import pallas_kernels as pk
from distributed_ghs_implementation_tpu.ops.segment_ops import fragment_moe
from distributed_ghs_implementation_tpu.ops.union_find import hook_and_compress

INT32_MAX = np.iinfo(np.int32).max

STRATEGIES = ("ell", "fused", "stepped")


@pytest.fixture(autouse=True)
def _clean_kernel_state(monkeypatch):
    """Each test sees a fresh process: no sticky fallback, no default, no
    ambient GHS_KERNEL from the invoking shell."""
    monkeypatch.delenv("GHS_KERNEL", raising=False)
    pk._reset_for_tests()
    yield
    pk._reset_for_tests()


@pytest.fixture()
def bus():
    BUS.enable()
    BUS.clear()
    yield BUS
    BUS.enable()
    BUS.clear()


def _solve_ids(g, strategy, kernel):
    ids, _, _ = solve_graph(g, strategy=strategy, kernel=kernel)
    return ids


# ---------------------------------------------------------------------------
# Unit parity: each fused kernel vs its two-step XLA form
# ---------------------------------------------------------------------------
def test_fused_ell_row_min_matches_xla_form():
    rng = np.random.default_rng(0)
    n, rows, width = 1000, 96, 8
    fragment = jnp.asarray(rng.integers(0, n, size=n), jnp.int32)
    verts = jnp.asarray(rng.integers(0, n, size=rows), jnp.int32)
    dstb = jnp.asarray(rng.integers(0, n, size=(rows, width)), jnp.int32)
    rankb = jnp.asarray(rng.integers(0, 10_000, size=(rows, width)), jnp.int32)
    assert pk.ell_shape_ok(n, rows, width)
    got = np.asarray(pk.fused_ell_row_min(fragment, verts, dstb, rankb))
    fv = fragment[verts]
    fd = fragment[dstb]
    want = np.asarray(
        jnp.min(jnp.where(fd != fv[:, None], rankb, INT32_MAX), axis=1)
    )
    np.testing.assert_array_equal(got, want)


def test_fused_ell_row_min_pad_rows_stay_inert():
    """All-sentinel pad rows come out INT32_MAX — inert under scatter-min."""
    n, rows, width = 64, 16, 4
    fragment = jnp.arange(n, dtype=jnp.int32)
    verts = jnp.zeros(rows, jnp.int32)
    dstb = jnp.zeros((rows, width), jnp.int32)  # dst frag == src frag
    rankb = jnp.full((rows, width), INT32_MAX, jnp.int32)
    got = np.asarray(pk.fused_ell_row_min(fragment, verts, dstb, rankb))
    assert (got == INT32_MAX).all()


def test_fused_gather_key_matches_xla_form():
    rng = np.random.default_rng(1)
    n, e = 500, 1024  # e % 128 == 0 (the flat-shape contract)
    fragment = jnp.asarray(rng.integers(0, n, size=n), jnp.int32)
    src = jnp.asarray(rng.integers(0, n, size=e), jnp.int32)
    dst = jnp.asarray(rng.integers(0, n, size=e), jnp.int32)
    rank = jnp.asarray(rng.permutation(e), jnp.int32)
    assert pk.flat_shape_ok(n, e)
    fsrc, key = pk.fused_gather_key(fragment, src, dst, rank)
    f_src = fragment[src]
    f_dst = fragment[dst]
    np.testing.assert_array_equal(np.asarray(fsrc), np.asarray(f_src))
    np.testing.assert_array_equal(
        np.asarray(key),
        np.asarray(jnp.where(f_src != f_dst, rank, INT32_MAX)),
    )


@pytest.mark.parametrize("n", [1000, 1024])  # with and without lane padding
def test_fused_hook_compress_matches_hook_and_compress(n):
    """Real hook forests (from a genuine MOE round, so cycles are only
    mutual pairs) land on the identical (new_fragment, parent_star)."""
    rng = np.random.default_rng(n)
    g = gnm_random_graph(n, 4 * n, seed=int(rng.integers(1 << 30)))
    src, dst, rank, ra, rb = _staged_arrays(g)
    fragment = jnp.arange(g.num_nodes, dtype=jnp.int32)
    has, _moe_rank, dst_frag = fragment_moe(fragment, src, dst, rank, ra, rb)
    newf_x, par_x = hook_and_compress(has, dst_frag, fragment, kernel="xla")
    newf_p, par_p = pk.fused_hook_compress(has, dst_frag, fragment)
    np.testing.assert_array_equal(np.asarray(newf_p), np.asarray(newf_x))
    np.testing.assert_array_equal(np.asarray(par_p), np.asarray(par_x))


def _staged_arrays(g):
    from distributed_ghs_implementation_tpu.models.boruvka import (
        prepare_device_arrays,
    )

    _, src, dst, rank, ra, rb = prepare_device_arrays(g)
    return src, dst, rank, ra, rb


# ---------------------------------------------------------------------------
# Shape guards: guarded geometries take the XLA form, never an error
# ---------------------------------------------------------------------------
def test_shape_guards():
    geom = pk.geometry()
    assert not pk.hook_shape_ok(0)
    assert not pk.hook_shape_ok(geom.hook_max_nodes + 1)
    assert pk.hook_shape_ok(geom.hook_max_nodes)
    assert not pk.flat_shape_ok(100, 130)  # not a lane multiple
    assert not pk.flat_shape_ok(100, 64)  # under one lane row
    assert not pk.flat_shape_ok(geom.table_max_elems + 1, 1024)
    assert pk.flat_shape_ok(100, 128)
    assert not pk.ell_shape_ok(0, 4, 4)
    assert not pk.ell_shape_ok(geom.table_max_elems + 1, 4, 4)
    assert pk.ell_shape_ok(100, 4, 4)


def test_guarded_geometry_still_solves_under_pallas_request():
    """A graph whose slot count fails the flat guard must still solve
    correctly with kernel='pallas' — the guard routes it to XLA inline."""
    g = gnm_random_graph(50, 60, seed=3)
    ids_x = _solve_ids(g, "stepped", "xla")
    ids_p = _solve_ids(g, "stepped", "pallas")
    np.testing.assert_array_equal(ids_p, ids_x)


# ---------------------------------------------------------------------------
# Selection semantics
# ---------------------------------------------------------------------------
def test_kernel_choice_auto_never_interprets_for_throughput():
    # CPU CI: probe passes (interpret mode), but auto must still pick xla.
    assert pk.pallas_supported()
    assert pk.kernel_choice() == "xla"
    assert pk.kernel_choice("auto") == "xla"


def test_kernel_choice_explicit_pallas_uses_interpret_probe():
    assert pk.kernel_choice("pallas") == "pallas"
    assert pk.kernel_choice("xla") == "xla"


def test_kernel_choice_env_default_and_override(monkeypatch):
    monkeypatch.setenv("GHS_KERNEL", "pallas")
    assert pk.kernel_choice() == "pallas"
    # Process default (serve --kernel) wins over the env var.
    pk.set_default_kernel("xla")
    assert pk.kernel_choice() == "xla"
    # Per-solve override wins over both.
    assert pk.kernel_choice("pallas") == "pallas"
    # "auto" default clears back to env resolution.
    pk.set_default_kernel("auto")
    assert pk.kernel_choice() == "pallas"


def test_kernel_choice_rejects_garbage(monkeypatch):
    with pytest.raises(ValueError):
        pk.kernel_choice("mosaic")
    with pytest.raises(ValueError):
        pk.set_default_kernel("fast")
    monkeypatch.setenv("GHS_KERNEL", "banana")
    with pytest.raises(ValueError):
        pk.kernel_choice()


def test_disable_pallas_is_sticky_and_counted(bus):
    pk.disable_pallas("test: simulated mosaic failure")
    assert pk.kernel_choice("pallas") == "xla"
    assert not pk.pallas_supported()
    assert bus.counters().get("kernel.fallback") == 1
    pk.disable_pallas("second trip")  # idempotent: no double count
    assert bus.counters().get("kernel.fallback") == 1
    report = pk.kernel_report()
    assert report["resolved"] == "xla"
    assert "simulated mosaic failure" in report["disabled_reason"]
    pk._reset_for_tests()  # simulated restart clears the latch
    assert pk.kernel_choice("pallas") == "pallas"


# ---------------------------------------------------------------------------
# End-to-end parity: edge-for-edge identical MSTs
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("scale", [12, 14])
@pytest.mark.parametrize("strategy", STRATEGIES)
def test_rmat_parity(scale, strategy):
    g = rmat_graph(scale, 16, seed=24)
    np.testing.assert_array_equal(
        _solve_ids(g, strategy, "pallas"), _solve_ids(g, strategy, "xla")
    )


@pytest.mark.slow
@pytest.mark.parametrize("scale", [16, 18])
@pytest.mark.parametrize("strategy", STRATEGIES)
def test_rmat_parity_large(scale, strategy):
    g = rmat_graph(scale, 16, seed=24)
    np.testing.assert_array_equal(
        _solve_ids(g, strategy, "pallas"), _solve_ids(g, strategy, "xla")
    )


# Adversarial shapes from the fuzz net: pow2-straddling sizes, all-equal
# weights (pure tie-break), dense multigraphs, single edges, disconnection.
FUZZ_CASES = [
    (16, 15, 3),
    (17, 40, 2),
    (33, 33, 1),
    (257, 2048, 5),
    (64, 1, 7),
    (40, 4000, 4),
]


@pytest.mark.parametrize("n,m,wmax", FUZZ_CASES)
def test_fuzz_parity(n, m, wmax):
    rng = np.random.default_rng(n * 31 + m)
    g = Graph.from_arrays(
        n,
        rng.integers(0, n, size=m),
        rng.integers(0, n, size=m),
        rng.integers(1, wmax + 1, size=m),
    )
    if g.num_edges == 0:
        pytest.skip("degenerate draw: every edge was a self-loop")
    for strategy in STRATEGIES:
        np.testing.assert_array_equal(
            _solve_ids(g, strategy, "pallas"),
            _solve_ids(g, strategy, "xla"),
            err_msg=strategy,
        )


def test_rank_sharded_parity_8dev_dryrun():
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    g = gnm_random_graph(9000, 36000, seed=5)
    ids_x, _, _ = solve_graph_rank_sharded(g, kernel="xla")
    ids_p, _, _ = solve_graph_rank_sharded(g, kernel="pallas")
    np.testing.assert_array_equal(np.sort(ids_p), np.sort(ids_x))
    ids_ref, _, _ = solve_graph(g, kernel="xla")
    np.testing.assert_array_equal(np.sort(ids_p), ids_ref)


# ---------------------------------------------------------------------------
# Lane cache, compile taxonomy, warmup coverage
# ---------------------------------------------------------------------------
def test_lane_kernel_variants_cache_separately_and_agree(bus):
    graphs = [gnm_random_graph(128, 480, seed=60 + i) for i in range(4)]
    clear_solver_cache()
    out_x = solve_lanes(graphs, lanes=4, kernel="xla")
    out_p = solve_lanes(graphs, lanes=4, kernel="pallas")
    for (ids_x, frag_x, _), (ids_p, frag_p, _) in zip(out_x, out_p):
        np.testing.assert_array_equal(ids_p, ids_x)
        np.testing.assert_array_equal(frag_p, frag_x)
    # Two compiles, one per variant, both under the same public 4-key.
    kernels = {k[4] for k in _SOLVER_CACHE}
    assert kernels == {"xla", "pallas"}
    assert len(compiled_bucket_keys()) == 1
    counters = bus.counters()
    assert counters.get("compile.kernel.xla") == 1
    assert counters.get("compile.kernel.pallas") == 1


def test_warmed_kernel_variant_is_a_request_time_hit(bus):
    from distributed_ghs_implementation_tpu.batch.warmup import (
        WarmupPlan,
        bucket_of,
        run_warmup,
    )

    clear_solver_cache()
    plan = WarmupPlan(
        buckets=(bucket_of(128, 480),), lanes=4, kernel="pallas",
        warm_single=False,
    )
    report = run_warmup(plan)
    assert report["kernel"] == "pallas"
    assert report["compiled"] == 1
    BUS.clear()
    graphs = [gnm_random_graph(128, 480, seed=90 + i) for i in range(4)]
    solve_lanes(graphs, lanes=4, kernel="pallas")
    counters = BUS.counters()
    assert counters.get("compile.miss", 0) == 0
    assert counters.get("compile.hit") == 1


def test_plan_from_flags_threads_kernel():
    from distributed_ghs_implementation_tpu.batch.warmup import plan_from_flags

    plan = plan_from_flags(buckets="128x480", lanes=4, kernel="pallas")
    assert plan.kernel == "pallas"
    plan = plan_from_flags(buckets="128x480", lanes=4, kernel="auto")
    assert plan.kernel is None


# ---------------------------------------------------------------------------
# Sticky runtime fallback: a Pallas failure degrades, never fails.
# The failure is injected at the solver-construction layer (a Mosaic
# lowering regression surfaces exactly there): bombing the traced kernel
# body itself is not deterministic, because jax's jit cache can satisfy a
# retrace from an earlier test's jaxpr without re-entering the body.
# ---------------------------------------------------------------------------
def test_lane_compile_failure_falls_back_and_answers(bus, monkeypatch):
    import distributed_ghs_implementation_tpu.batch.lanes as lanes_mod

    graphs = [gnm_random_graph(128, 480, seed=70 + i) for i in range(4)]
    clear_solver_cache()
    want = solve_lanes(graphs, lanes=4, kernel="xla")
    clear_solver_cache()
    real = lanes_mod._compile_bucket

    def boom(n_pad, m_pad, lanes, mode, kernel):
        if kernel == "pallas":
            raise RuntimeError("simulated mosaic lowering failure")
        return real(n_pad, m_pad, lanes, mode, kernel)

    monkeypatch.setattr(lanes_mod, "_compile_bucket", boom)
    got = execute_stacked(stack_lanes(graphs, lanes=4), kernel="pallas")
    for (ids_w, _, _), (ids_g, _, _) in zip(want, got):
        np.testing.assert_array_equal(ids_g, ids_w)
    assert bus.counters().get("kernel.fallback") == 1
    assert pk.kernel_choice("pallas") == "xla"  # sticky for the process


def test_warmup_compile_failure_falls_back_and_boots(bus, monkeypatch):
    """A Pallas failure during the warmup phase must degrade the process
    to XLA and keep warming — serve boot never dies on a kernel the
    process won't run (the request-path contract, applied at boot)."""
    import distributed_ghs_implementation_tpu.batch.warmup as warmup_mod
    from distributed_ghs_implementation_tpu.batch.warmup import (
        WarmupPlan,
        bucket_of,
        run_warmup,
    )

    clear_solver_cache()
    real = warmup_mod.precompile_bucket

    def boom(n_pad, m_pad, lanes, mode="fused", kernel=None):
        if kernel == "pallas":
            raise RuntimeError("simulated mosaic lowering failure")
        return real(n_pad, m_pad, lanes, mode, kernel=kernel)

    monkeypatch.setattr(warmup_mod, "precompile_bucket", boom)
    plan = WarmupPlan(
        buckets=(bucket_of(128, 480),), lanes=4, kernel="pallas",
        warm_single=False,
    )
    report = run_warmup(plan)
    assert report["kernel"] == "xla"  # repinned mid-phase
    assert report["compiled"] == 1  # the bucket still warmed, on XLA
    assert bus.counters().get("kernel.fallback") == 1
    assert pk.kernel_choice("pallas") == "xla"  # sticky for serving too


def test_sharded_lane_failure_falls_back_and_answers(bus, monkeypatch):
    import distributed_ghs_implementation_tpu.parallel.lane as lane_mod

    g = gnm_random_graph(9000, 36000, seed=6)
    want, _, _ = solve_graph(g, kernel="xla")
    real = lane_mod.make_rank_sharded_head

    def boom(mesh, kernel="xla"):
        if kernel == "pallas":
            raise RuntimeError("simulated mosaic lowering failure")
        return real(mesh, kernel)

    monkeypatch.setattr(lane_mod, "make_rank_sharded_head", boom)
    lane = lane_mod.ShardedLane(kernel="pallas")
    assert lane.kernel == "pallas"
    ids, _, _ = lane.solve(g)
    np.testing.assert_array_equal(ids, want)
    assert lane.kernel == "xla"  # repinned: later dispatches stay XLA
    assert bus.counters().get("kernel.fallback") == 1


def test_solve_graph_failure_falls_back_and_answers(bus, monkeypatch):
    import distributed_ghs_implementation_tpu.models.boruvka as bz

    g = gnm_random_graph(512, 2048, seed=9)
    want, _, _ = solve_graph(g, strategy="fused", kernel="xla")
    real = bz._solve_from_iota

    def boom(*args, **kwargs):
        if kwargs.get("kernel") == "pallas":
            raise RuntimeError("simulated mosaic dispatch failure")
        return real(*args, **kwargs)

    monkeypatch.setattr(bz, "_solve_from_iota", boom)
    got, _, _ = solve_graph(g, strategy="fused", kernel="pallas")
    np.testing.assert_array_equal(got, want)
    assert bus.counters().get("kernel.fallback") == 1
    assert pk.kernel_choice() == "xla"
