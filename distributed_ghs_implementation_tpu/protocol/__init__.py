"""The GHS message protocol, as one state machine with pluggable transport.

This is the message-level view of the algorithm the reference implements twice
— once per backend (``/root/reference/ghs_implementation.py:118-413`` for
threads, ``ghs_implementation_mpi.py:117-757`` for MPI), a duplication
SURVEY.md §2 flags as the design smell to fix. Here the protocol lives in
:class:`~distributed_ghs_implementation_tpu.protocol.node.GHSNode` once, and
transports deliver messages. The bundled
:class:`~distributed_ghs_implementation_tpu.protocol.transport.SimTransport`
is a deterministic discrete-event queue: unlike the reference's thread/MPI
runtimes, identical runs deliver identical message orders, so protocol
behavior is testable and the liveness heuristics the reference needs (requeue
caps, idle termination, stuck-root retries — its source of wrong MSTs) do not
exist.

The batched Borůvka kernel (``models/boruvka.py``) is the production path;
this backend exists for protocol parity, testing, and teaching.
"""

from distributed_ghs_implementation_tpu.protocol.faults import (
    FaultSpec,
    FaultyTransport,
    ReliableTransport,
)
from distributed_ghs_implementation_tpu.protocol.messages import (
    EdgeState,
    Message,
    MessageType,
    NodeState,
)
from distributed_ghs_implementation_tpu.protocol.node import GHSNode
from distributed_ghs_implementation_tpu.protocol.runner import (
    run_protocol,
    solve_graph_protocol,
)
from distributed_ghs_implementation_tpu.protocol.transport import SimTransport

__all__ = [
    "EdgeState",
    "FaultSpec",
    "FaultyTransport",
    "GHSNode",
    "Message",
    "MessageType",
    "NodeState",
    "ReliableTransport",
    "SimTransport",
    "run_protocol",
    "solve_graph_protocol",
]
