"""Result reporting: JSON artifacts and console tables.

Schema parity with the reference's two artifacts:

* ``mst_result.json`` — per-run result matching ``mst_result_mpi.json``
  (``/root/reference/ghs_implementation_mpi.py:810-822``): ``mst_edges``,
  ``total_weight``, ``num_nodes``, ``num_edges_in_mst``, ``expected_edges``,
  plus framework extras under stable keys.
* ``ghs_experiments.json`` — experiment-suite dump matching
  ``ghs_implementation.py:766-776,829-830``: per-experiment ``num_nodes``,
  ``num_edges``, ``ghs_weight``, ``nx_weight``, ``is_correct``,
  ``execution_time``.
"""

from __future__ import annotations

import json
from typing import List

from distributed_ghs_implementation_tpu.api import MSTResult


def result_to_dict(result: MSTResult) -> dict:
    out = {
        "mst_edges": [[int(a), int(b)] for a, b in result.edges],
        "total_weight": result.total_weight,
        "num_nodes": result.graph.num_nodes,
        "num_edges_in_mst": result.num_edges,
        "expected_edges": result.graph.num_nodes - result.num_components,
        "num_components": result.num_components,
        "num_levels": result.num_levels,
        "backend": result.backend,
        "execution_time": result.wall_time_s,
    }
    if result.incidents is not None:
        # Persist the supervised attempt/fallback trail with the artifact —
        # a degraded run must stay diagnosable after the process exits. The
        # one-line summary rides along so serve responses (and anything else
        # embedding this dict) report degradation without parsing records.
        out["incidents"] = result.incidents.to_dicts()
        out["incident_summary"] = result.incidents.summary()
    return out


def write_result_json(result: MSTResult, path: str) -> str:
    with open(path, "w") as f:
        json.dump(result_to_dict(result), f, indent=2)
    return path


def experiment_record(
    result: MSTResult, expected_weight: float, index: int = 0
) -> dict:
    """One row of the experiment suite (``ghs_implementation.py:766-776``)."""
    return {
        "experiment": index,
        "num_nodes": result.graph.num_nodes,
        "num_edges": result.graph.num_edges,
        "ghs_weight": result.total_weight,
        "nx_weight": expected_weight,
        "is_correct": abs(float(result.total_weight) - float(expected_weight)) < 1e-6
        and result.num_edges == result.graph.num_nodes - result.num_components,
        "execution_time": result.wall_time_s,
        "num_levels": result.num_levels,
        "backend": result.backend,
    }


def write_experiments_json(records: List[dict], path: str) -> str:
    with open(path, "w") as f:
        json.dump(records, f, indent=2)
    return path


def print_summary_table(records: List[dict]) -> None:
    """PASS/FAIL table matching the reference's console summary
    (``ghs_implementation.py:820-826``)."""
    print("=" * 72)
    print(f"{'#':>3} {'nodes':>7} {'edges':>9} {'weight':>10} {'oracle':>10} "
          f"{'time(s)':>9} {'result':>7}")
    print("-" * 72)
    for r in records:
        status = "PASS" if r["is_correct"] else "FAIL"
        print(
            f"{r['experiment']:>3} {r['num_nodes']:>7} {r['num_edges']:>9} "
            f"{r['ghs_weight']:>10} {r['nx_weight']:>10} "
            f"{r['execution_time']:>9.3f} {status:>7}"
        )
    print("=" * 72)
    passed = sum(1 for r in records if r["is_correct"])
    print(f"{passed}/{len(records)} experiments passed")
