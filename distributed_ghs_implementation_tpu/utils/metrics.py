"""Per-level structured metrics and profiler hooks.

The reference's observability is print-narration (per-message logs at
``/root/reference/ghs_implementation_mpi.py:100-113``, heartbeats ``:728-734``)
— unusable at scale and absent on the thread backend. The TPU equivalent
(SURVEY.md §5): structured per-level records (fragments remaining, edges
alive, level latency) from the host-stepped solver, plus a context manager
around ``jax.profiler`` for device traces viewable in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import time
from typing import List

import numpy as np


@dataclasses.dataclass
class LevelMetrics:
    level: int
    fragments_before: int
    fragments_after: int
    edges_alive_after: int
    wall_time_s: float


@dataclasses.dataclass
class SolveMetrics:
    num_nodes: int
    num_edges: int
    levels: List[LevelMetrics]
    total_wall_time_s: float

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)


def solve_graph_instrumented(
    graph, *, compact: bool = True, strategy: str = "stepped"
) -> tuple:
    """Like ``models.boruvka.solve_graph`` but returns ``(result_tuple,
    SolveMetrics)``.

    ``strategy="stepped"`` records one entry per level (host-stepped
    execution); ``strategy="rank"`` uses the fast rank solver and records one
    entry per chunk boundary (its hook granularity) — the practical choice at
    bench scale where the stepped kernel is not a usable host.
    """
    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        empty = (np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0)
        return empty, SolveMetrics(n, graph.num_edges, [], 0.0)

    if strategy == "rank":
        return _solve_rank_instrumented(graph)
    if strategy != "stepped":
        raise ValueError(f"unknown strategy {strategy!r}; expected stepped|rank")

    from distributed_ghs_implementation_tpu.models.boruvka import (
        prepare_device_arrays,
        solve_arrays_stepped,
    )

    args = prepare_device_arrays(graph)
    records: List[LevelMetrics] = []
    frags_before = [n]

    def on_level(level, fragment, mst_ranks, has, count, dt):
        frags_after = int(np.unique(np.asarray(fragment)[:n]).size)
        records.append(
            LevelMetrics(
                level=level,
                fragments_before=frags_before[0],
                fragments_after=frags_after,
                # The stepped kernel counts surviving *directed slots*; each
                # undirected edge occupies two, so halve for the edge count.
                edges_alive_after=count // 2,
                wall_time_s=dt,
            )
        )
        frags_before[0] = frags_after

    t_start = time.perf_counter()
    mst_ranks, fragment, levels = solve_arrays_stepped(
        *args, compact=compact, stepped_levels=None, on_level=on_level
    )
    total = time.perf_counter() - t_start

    ranks_chosen = np.nonzero(np.asarray(mst_ranks))[0]
    edge_ids = np.sort(graph.edge_id_of_rank(ranks_chosen))
    result = (edge_ids, np.asarray(fragment)[:n], levels)
    return result, SolveMetrics(n, graph.num_edges, records, total)


def _solve_rank_instrumented(graph) -> tuple:
    """Rank-solver instrumentation via its ``on_chunk`` hook (chunk-boundary
    granularity; the alive count there is undirected already)."""
    from distributed_ghs_implementation_tpu.models.rank_solver import (
        make_production_solver,
    )

    n = graph.num_nodes
    records = []
    frags_before = [n]
    last = [time.perf_counter()]

    def on_chunk(level, fragment, mst_ranks, count):
        now = time.perf_counter()
        frags_after = int(np.unique(np.asarray(fragment)[:n]).size)
        records.append(
            LevelMetrics(
                level=level,
                fragments_before=frags_before[0],
                fragments_after=frags_after,
                edges_alive_after=count,
                wall_time_s=now - last[0],
            )
        )
        frags_before[0] = frags_after
        last[0] = now

    # make_production_solver is the single routing source shared with
    # solve_graph_rank: the instrumented path measures the kernels
    # production runs (passing on_chunk selects the chunked forms — the
    # speculative single-dispatch variant has no boundaries to instrument).
    solve = make_production_solver(graph)
    last[0] = time.perf_counter()
    t_start = last[0]
    mst_ranks, fragment, levels = solve(on_chunk=on_chunk)
    total = time.perf_counter() - t_start

    ranks_chosen = np.nonzero(np.asarray(mst_ranks))[0]
    edge_ids = np.sort(graph.edge_id_of_rank(ranks_chosen))
    result = (edge_ids, np.asarray(fragment)[:n], levels)
    return result, SolveMetrics(n, graph.num_edges, records, total)


@contextlib.contextmanager
def profiler_trace(log_dir: str):
    """Wrap a solve in a JAX device profile (TensorBoard/Perfetto trace).

    >>> with profiler_trace("/tmp/ghs-trace"):
    ...     minimum_spanning_forest(graph)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
