"""Sharded backend on the 8-virtual-device CPU mesh.

The multi-chip path must produce byte-identical results to the single-device
kernel (the cross-backend parity test the reference approximates with
``test_thread_on_mpi_graph.py``, upgraded from edge-count to exact equality).
"""

import os

import jax
import numpy as np
import pytest

from distributed_ghs_implementation_tpu.api import minimum_spanning_forest
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.graphs.generators import (
    erdos_renyi_graph,
    line_graph,
    readme_sample_graph,
    rmat_graph,
)
from distributed_ghs_implementation_tpu.parallel.mesh import edge_mesh
from distributed_ghs_implementation_tpu.parallel.sharded import solve_graph_sharded
from distributed_ghs_implementation_tpu.utils.verify import verify_result


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    assert edge_mesh().devices.size == 8


def test_sharded_readme_sample():
    r = minimum_spanning_forest(readme_sample_graph(), backend="sharded")
    assert r.total_weight == 20
    assert sorted(r.edges) == [(0, 1), (1, 2), (2, 3), (3, 4), (3, 5)]


@pytest.mark.parametrize("seed", range(5))
def test_sharded_matches_device_exactly(seed):
    g = erdos_renyi_graph(150, 0.06, seed=seed)
    rs = minimum_spanning_forest(g, backend="sharded")
    rd = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(rs.edge_ids, rd.edge_ids)
    assert verify_result(rs).ok


def test_sharded_rmat_scipy_parity():
    g = rmat_graph(11, 8, seed=6)
    r = minimum_spanning_forest(g, backend="sharded")
    assert verify_result(r, oracle="scipy").ok


def test_sharded_high_diameter():
    r = minimum_spanning_forest(line_graph(300), backend="sharded")
    assert r.num_edges == 299


def test_sharded_disconnected():
    g = Graph.from_edges(6, [(0, 1, 1), (1, 2, 2), (3, 4, 1), (4, 5, 5)])
    r = minimum_spanning_forest(g, backend="sharded")
    assert r.num_components == 2 and r.num_edges == 4


@pytest.mark.parametrize("seed", range(3))
def test_sharded_ell_matches_fused(seed):
    """Vertex-sharded ELL kernel vs single-device fused kernel."""
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.parallel.sharded import (
        solve_graph_sharded_ell,
    )

    g = rmat_graph(9, 8, seed=seed, use_native=False)
    a = solve_graph_sharded_ell(g)
    b = solve_graph(g, strategy="fused")
    assert np.array_equal(a[0], b[0])


def test_sharded_ell_star_hub():
    """A deg-39 hub shards its ELL row block across devices without skew."""
    from distributed_ghs_implementation_tpu.models.boruvka import solve_graph
    from distributed_ghs_implementation_tpu.parallel.sharded import (
        solve_graph_sharded_ell,
    )

    g = Graph.from_edges(40, [(0, i, i) for i in range(1, 40)])
    a = solve_graph_sharded_ell(g)
    b = solve_graph(g, strategy="fused")
    assert np.array_equal(a[0], b[0])


def test_sharded_submesh():
    """A 4-device submesh also works (mesh size independent of graph)."""
    g = erdos_renyi_graph(64, 0.15, seed=3)
    mesh = edge_mesh(num_devices=4)
    edge_ids, fragment, levels = solve_graph_sharded(g, mesh=mesh)
    rd = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(edge_ids, rd.edge_ids)


@pytest.mark.parametrize("seed", range(3))
def test_rank_sharded_matches_device(seed):
    """Sharded rank-space solver (the fast multi-chip path) vs single-device."""
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    g = rmat_graph(12, 8, seed=seed, use_native=False)
    ids, frag, lv = solve_graph_rank_sharded(g)
    rd = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(ids, rd.edge_ids)
    assert verify_result(rd, oracle="scipy").ok


def test_rank_sharded_high_diameter():
    """Grid graph: exercises multiple compact/all-gather finish rounds."""
    from distributed_ghs_implementation_tpu.graphs.generators import road_grid_graph
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )
    from distributed_ghs_implementation_tpu.utils.verify import scipy_mst_weight

    g = road_grid_graph(60, 60, seed=8)
    ids, frag, lv = solve_graph_rank_sharded(g)
    assert float(g.w[ids].sum()) == scipy_mst_weight(g)
    assert np.unique(frag).size == 1


def test_rank_sharded_disconnected_and_isolated():
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    g = Graph.from_edges(9, [(0, 1, 1), (1, 2, 2), (3, 4, 1), (4, 5, 5)])
    ids, frag, lv = solve_graph_rank_sharded(g)
    assert len(ids) == 4
    assert np.unique(frag).size == 5  # two trees + three isolated vertices


@pytest.mark.parametrize("seed", range(3))
def test_rank_sharded_filtered_matches_device(seed):
    """The sharded filter-Kruskal path (forced on below its size threshold)
    must match the single-device solve exactly."""
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    g = rmat_graph(12, 12, seed=seed, use_native=False)
    ids, frag, lv = solve_graph_rank_sharded(g, filtered=True)
    rd = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(ids, rd.edge_ids)
    assert verify_result(rd, oracle="scipy").ok


def test_rank_sharded_filtered_edge_cases():
    """Filtered sharded path on awkward shapes: disconnected forest with
    isolated vertices, a submesh, heavy ties."""
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    # Disconnected forest with isolated vertices, big enough that the
    # 2*prefix <= m_pad guard actually routes through the filtered path
    # (two dense 40-vertex halves, 10 isolated vertices, no bridge).
    rng0 = np.random.default_rng(21)
    g = Graph.from_arrays(
        90,
        np.concatenate([rng0.integers(0, 40, 900), rng0.integers(40, 80, 900)]),
        np.concatenate([rng0.integers(0, 40, 900), rng0.integers(40, 80, 900)]),
        rng0.integers(1, 500, 1800),
    )
    ids, frag, lv = solve_graph_rank_sharded(g, filtered=True)
    rd0 = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(ids, rd0.edge_ids)
    assert np.unique(frag).size == rd0.num_components
    assert np.unique(frag).size >= 12  # two components + 10 isolated

    g2 = erdos_renyi_graph(80, 0.3, seed=5)
    mesh = edge_mesh(num_devices=4)
    ids, frag, lv = solve_graph_rank_sharded(g2, mesh=mesh, filtered=True)
    rd = minimum_spanning_forest(g2, backend="device")
    assert np.array_equal(ids, rd.edge_ids)

    rng = np.random.default_rng(11)
    g3 = Graph.from_arrays(
        200,
        rng.integers(0, 200, 3000),
        rng.integers(0, 200, 3000),
        np.ones(3000, dtype=np.int64),
    )
    ids, frag, lv = solve_graph_rank_sharded(g3, filtered=True)
    rd = minimum_spanning_forest(g3, backend="device")
    assert np.array_equal(ids, rd.edge_ids)


def test_rank_sharded_submesh():
    from distributed_ghs_implementation_tpu.parallel.rank_sharded import (
        solve_graph_rank_sharded,
    )

    g = erdos_renyi_graph(80, 0.12, seed=5)
    mesh = edge_mesh(num_devices=4)
    ids, frag, lv = solve_graph_rank_sharded(g, mesh=mesh)
    rd = minimum_spanning_forest(g, backend="device")
    assert np.array_equal(ids, rd.edge_ids)


def test_rank64_split_key_child():
    """VERDICT r4 item 6: the 2^31+ rank envelope on the mesh path. Ranks
    travel as int32 (shard, local) split keys — the same all-int32 device
    program at any scale — validated byte-identical against the int32
    sharded and single-chip solves in a child interpreter (isolated
    virtual-device config). The child also pins the capacity-guard loop
    and first_ranks64 sentinel semantics."""
    import subprocess
    import sys

    child = os.path.join(os.path.dirname(__file__), "_rank64_child.py")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    proc = subprocess.run(
        [sys.executable, child], capture_output=True, text=True, timeout=560,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rank64 child ok" in proc.stdout


def test_filtered_fused_overflow_fallback(monkeypatch):
    """The fused filter+compact speculates the per-shard survivor width;
    when a shard overflows it must fall back to the exact two-step filter
    (and from there the capacity guard), landing on the identical MST."""
    from distributed_ghs_implementation_tpu.parallel import rank_sharded as rsh

    g = rmat_graph(11, 16, seed=9)
    ref = np.sort(minimum_spanning_forest(g, backend="device").edge_ids)
    used = []
    orig = rsh.make_rank_filter_relabel

    def spying(mesh, prefix):
        used.append(1)
        return orig(mesh, prefix)

    monkeypatch.setattr(rsh, "make_rank_filter_relabel", spying)
    # Tiny gather budget -> tiny speculative width -> guaranteed overflow.
    monkeypatch.setattr(rsh, "_FINISH_GATHER_MAX_SLOTS", 64)
    ids, _, _ = rsh.solve_graph_rank_sharded(g, filtered=True)
    assert used, "overflow did not reach the two-step fallback"
    assert np.array_equal(np.sort(ids), ref)
