"""Content checksums for persisted artifacts: sha256 sidecars + quarantine.

Every npz the stack persists (serve result store entries, stream
snapshots, solver checkpoints) is written through
``utils.checkpoint.atomic_write_npz``, which — as of round 19 — also
writes a ``<path>.sha256`` sidecar holding the hex digest of the final
file bytes. Loads verify the sidecar BEFORE deserializing: a mismatch
means the bytes changed after the commit point (bit rot, a torn
filesystem, an overwrite race nothing else caught) and the file must not
be parsed — ``np.load`` on garbage can throw from deep inside zlib, or
worse, succeed and hand back plausible wrong arrays.

Verification outcomes:

* ``"ok"`` — sidecar present and matching.
* ``"unverified"`` — no sidecar (a pre-round-19 file, or a crash landed
  between the data rename and the sidecar write). Accepted: refusing
  every legacy file on upgrade would be a self-inflicted cache wipe. The
  caller's counter (e.g. ``serve.store.unverified``) keeps the exposure
  visible.
* :class:`IntegrityError` — sidecar present and WRONG. The caller
  quarantines the file (:func:`quarantine` moves it — and its sidecar —
  into a ``.quarantine/`` subdirectory next to it, preserving the
  evidence for postmortems) and degrades to a miss.

Checksums are over raw file bytes, not parsed content, so verification
never allocates array-sized buffers for corrupt input.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import tempfile
from typing import Optional

from distributed_ghs_implementation_tpu.obs.events import BUS

SIDECAR_SUFFIX = ".sha256"
QUARANTINE_DIR = ".quarantine"
#: Quarantined generations retained per directory (oldest reaped first):
#: evidence, not an archive.
QUARANTINE_CAP = 64


class IntegrityError(ValueError):
    """A file's bytes do not match its recorded checksum."""

    def __init__(self, path: str, expected: str, actual: str):
        super().__init__(
            f"checksum mismatch for {path}: sidecar says {expected[:16]}..., "
            f"file hashes to {actual[:16]}..."
        )
        self.path = path
        self.expected = expected
        self.actual = actual


def sidecar_path(path: str) -> str:
    return path + SIDECAR_SUFFIX


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_sidecar(path: str, digest: Optional[str] = None) -> str:
    """Record ``path``'s checksum in its sidecar (tmp + rename — readers
    see the old sidecar or the new one, never a torn hex string)."""
    if digest is None:
        digest = sha256_file(path)
    side = sidecar_path(path)
    d = os.path.dirname(os.path.abspath(side)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".sha256.tmp")
    try:
        os.write(fd, (digest + "\n").encode())
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, side)
    return side


def read_sidecar(path: str) -> Optional[str]:
    try:
        with open(sidecar_path(path)) as f:
            digest = f.read().strip()
    except OSError:
        return None
    return digest or None


def check_file(path: str) -> str:
    """Verify ``path`` against its sidecar: ``"ok"`` / ``"unverified"``
    (no sidecar), raising :class:`IntegrityError` on a mismatch. The file
    must exist (propagates ``FileNotFoundError`` — absence is the
    caller's plain-miss path, never an integrity event)."""
    expected = read_sidecar(path)
    actual = sha256_file(path)  # also raises FileNotFoundError for caller
    if expected is None:
        return "unverified"
    if actual != expected:
        raise IntegrityError(path, expected, actual)
    return "ok"


def quarantine(
    path: str,
    *,
    reason: str = "",
    counter: Optional[str] = None,
) -> Optional[str]:
    """Move ``path`` (and its sidecar) into ``.quarantine/`` next to it.

    Returns the quarantined path, or ``None`` when the file was already
    gone (a concurrent reader quarantined it first — their move IS the
    outcome this one wanted). The move is ``os.replace`` within one
    directory tree: atomic, and a corrupt file can never be half-removed.
    """
    directory = os.path.dirname(os.path.abspath(path)) or "."
    qdir = os.path.join(directory, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    dest = os.path.join(qdir, os.path.basename(path))
    try:
        os.replace(path, dest)
    except FileNotFoundError:
        return None
    with contextlib.suppress(OSError):
        os.replace(sidecar_path(path), sidecar_path(dest))
    if counter:
        BUS.count(counter)
    BUS.instant(
        "integrity.quarantined", cat="integrity",
        path=os.path.basename(path), reason=reason or "checksum/corrupt",
    )
    _reap_quarantine(qdir)
    return dest


def _reap_quarantine(qdir: str) -> None:
    """Bound the evidence locker: oldest quarantined files past the cap
    are deleted (best-effort — a racing sibling's unlink is success)."""
    try:
        entries = [
            e for e in os.scandir(qdir)
            if e.is_file() and not e.name.endswith(SIDECAR_SUFFIX)
        ]
    except OSError:
        return
    if len(entries) <= QUARANTINE_CAP:
        return
    entries.sort(key=lambda e: e.stat().st_mtime)
    for entry in entries[: len(entries) - QUARANTINE_CAP]:
        for victim in (entry.path, sidecar_path(entry.path)):
            with contextlib.suppress(OSError):
                os.unlink(victim)


def list_quarantined(directory: str) -> list:
    """Quarantined basenames under ``directory`` (ops/drill visibility)."""
    qdir = os.path.join(directory, QUARANTINE_DIR)
    try:
        return sorted(
            e.name for e in os.scandir(qdir)
            if e.is_file() and not e.name.endswith(SIDECAR_SUFFIX)
        )
    except OSError:
        return []
