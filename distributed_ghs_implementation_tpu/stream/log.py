"""Durable update log: snapshot every K windows + a JSONL delta WAL.

A maintained forest used to live only in a worker's memory — a restart
threw away every windowed session and the first post-restart update paid a
full fresh solve. This module gives each stream a directory under the
(fleet-shared) stream root:

* ``snapshot.npz`` — the session's whole state (``u/v/w/in_tree`` +
  window sequence + head digest) written through
  :func:`utils.checkpoint.atomic_write_npz`: tmp-file + rename with one
  retained ``.bak`` generation, so a crash mid-snapshot costs at most one
  snapshot interval (the ``stream.log.save`` fault site tears writes in
  tests).
* ``wal.jsonl`` — one JSON line per committed window
  (``ghs-stream-wal-v1``: seq, prev/new digest, the raw updates). Appends
  are flushed + fsynced and serialized across processes by the same
  advisory per-path flock the shared result store uses
  (``serve.store._flocked``) — the two-process hammer test drives exactly
  that interleaving.

**Replay** (:meth:`UpdateLog.load`) is snapshot-then-deltas: the newest
loadable snapshot generation (primary, else ``.bak``) plus every WAL entry
with a later sequence number, in order. A torn tail — a crash mid-append
leaves a partial last line — is skipped and counted
(``stream.log.torn_skipped``), never fatal; so is an unparsable *mid*-log
line (``stream.log.corrupt_line`` — a retried append seals the torn
record of its failed predecessor in place, leaving garbage between two
good lines). A real chain break (sequence gap, or a ``prev`` digest that
does not follow from the snapshot — the snapshot/log-disagreement case)
stops replay at the break with ``stream.log.chain_broken``: everything
before the break is still recovered, and the caller decides whether the
shortened head is acceptable. After each snapshot the WAL is compacted (entries at or below
the snapshot's sequence dropped via tmp + rename); a crash between
snapshot and compaction just leaves already-covered entries that replay
skips by sequence number.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

import numpy as np

from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.utils.checkpoint import (
    atomic_write_npz,
)


def _flocked(path: str):
    """The shared advisory per-path write lock (``serve.store._flocked``),
    imported lazily: ``serve`` imports ``stream`` for the service verbs,
    so a module-level import here would close an import cycle."""
    from distributed_ghs_implementation_tpu.serve.store import (
        _flocked as flocked,
    )

    return flocked(path)

WAL_SCHEMA = "ghs-stream-wal-v1"
FAULT_SITE = "stream.log.save"


class ChainBreak(ValueError):
    """The WAL does not follow from the snapshot (gap or digest mismatch),
    or an append would not follow from the durable tail (a fork). Carries
    the durable head when known so the caller can re-sync the client."""

    def __init__(
        self,
        msg: str,
        *,
        seq: Optional[int] = None,
        digest: Optional[str] = None,
    ):
        super().__init__(msg)
        self.seq = seq
        self.digest = digest


def stream_dir(root: str, stream_id: str) -> str:
    return os.path.join(root, stream_id)


def list_streams(root: str) -> List[str]:
    """Stream ids with a recoverable directory under ``root``."""
    if not os.path.isdir(root):
        return []
    return sorted(
        e.name for e in os.scandir(root)
        if e.is_dir() and (
            os.path.exists(os.path.join(e.path, "snapshot.npz"))
            or os.path.exists(os.path.join(e.path, "snapshot.npz.bak"))
        )
    )


class UpdateLog:
    """One stream's durable layer: ``<root>/<stream_id>/{snapshot.npz,wal.jsonl}``."""

    def __init__(self, root: str, stream_id: str):
        self.dir = stream_dir(root, stream_id)
        self.snap_path = os.path.join(self.dir, "snapshot.npz")
        self.wal_path = os.path.join(self.dir, "wal.jsonl")

    # -- writing -------------------------------------------------------
    def append(
        self, *, seq: int, prev_digest: str, digest: str, updates: list
    ) -> None:
        """Append one committed window (flushed + fsynced, flock-serialized).

        The durable chain is validated under the same flock before the
        write: an append must extend the on-disk tail (last WAL entry,
        else the snapshot head). A mismatch raises :class:`ChainBreak`
        carrying the durable head instead of forking the log — the
        fleet-shared-``stream_dir`` race where a worker holding a stale
        resident copy of a stream accepts a publish (its *in-memory* head
        matched) after another worker already committed past it.
        """
        os.makedirs(self.dir, exist_ok=True)
        line = json.dumps({
            "schema": WAL_SCHEMA,
            "seq": int(seq),
            "prev": prev_digest,
            "digest": digest,
            "updates": updates,
        })
        with _flocked(self.wal_path):
            tail = self._durable_head()
            if tail is not None and (
                int(seq) != tail[0] + 1 or prev_digest != tail[1]
            ):
                BUS.count("stream.log.fork_refused")
                raise ChainBreak(
                    f"append seq {seq} (prev {prev_digest[:12]}...) does "
                    f"not extend the durable tail seq {tail[0]} "
                    f"({tail[1][:12]}...)",
                    seq=tail[0],
                    digest=tail[1],
                )
            # Seal a torn tail first: a crash mid-append leaves a partial
            # line with no trailing newline, and writing straight after it
            # would fuse this (durably committed) record onto the garbage,
            # making it unparsable on replay.
            seal = b""
            try:
                with open(self.wal_path, "rb") as rf:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        seal = b"\n"
                        BUS.count("stream.log.sealed_torn")
            except (FileNotFoundError, OSError):
                pass  # empty or missing: nothing to seal
            with open(self.wal_path, "ab") as f:
                f.write(seal + (line + "\n").encode())
                f.flush()
                os.fsync(f.fileno())
        BUS.count("stream.log.append")

    def snapshot(
        self,
        state: dict,
        *,
        seq: int,
        digest: str,
        notifications: Optional[list] = None,
    ) -> None:
        """Persist the session state (``WindowedMST.state_arrays``) and
        compact the WAL down to entries the snapshot does not cover.

        ``notifications`` rides along (JSON-encoded) so a recovered
        stream's ring reaches BACK past the snapshot point — a subscriber
        whose cursor predates the snapshot still drains gap-free after a
        failover, instead of hitting ``truncated``."""
        os.makedirs(self.dir, exist_ok=True)
        arrays = dict(state)
        arrays["seq"] = np.asarray(int(seq))
        arrays["digest"] = np.asarray(digest)
        arrays["notifications"] = np.asarray(
            json.dumps(list(notifications or []))
        )
        with _flocked(self.snap_path):
            atomic_write_npz(self.snap_path, arrays, fault_site=FAULT_SITE)
        BUS.count("stream.log.snapshot")
        self._compact(seq)

    def _compact(self, covered_seq: int) -> None:
        """Drop WAL entries the snapshot already covers (tmp + rename; a
        crash anywhere leaves entries replay skips by sequence number)."""
        try:
            with _flocked(self.wal_path):
                entries, _torn = self._read_wal()
                keep = [e for e in entries if e["seq"] > covered_seq]
                if len(keep) == len(entries):
                    return
                tmp = self.wal_path + ".tmp"
                with open(tmp, "w") as f:
                    for e in keep:
                        f.write(json.dumps({"schema": WAL_SCHEMA, **e}) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.wal_path)
            BUS.count("stream.log.compact")
        except (OSError, TimeoutError):
            pass  # compaction is best-effort; replay skips covered entries

    def _durable_head(self) -> Optional[Tuple[int, str]]:
        """``(seq, digest)`` of the durable chain tail — the last WAL
        append, else the newest loadable snapshot head; ``None`` when
        neither exists (a bare log). Callers hold the WAL flock; reads
        here must not re-enter it."""
        tail = self._tail_entry()
        if tail is not None:
            return tail["seq"], tail["digest"]
        for candidate in (self.snap_path, self.snap_path + ".bak"):
            try:
                with np.load(candidate) as data:
                    return int(data["seq"]), str(data["digest"])
            except Exception:  # missing/torn: fall through
                continue
        return None

    # -- reading -------------------------------------------------------
    @staticmethod
    def _parse_line(line: str) -> Optional[dict]:
        """One WAL line -> entry dict, or ``None`` for anything torn,
        unparsable, or schema-mismatched."""
        try:
            rec = json.loads(line)
            if rec.get("schema") != WAL_SCHEMA:
                raise ValueError(f"bad schema {rec.get('schema')!r}")
            return {
                "seq": int(rec["seq"]),
                "prev": rec["prev"],
                "digest": rec["digest"],
                "updates": rec["updates"],
            }
        except (ValueError, KeyError, TypeError):
            return None

    def _tail_entry(self) -> Optional[dict]:
        """Last complete, parsable WAL entry, found by a backwards chunked
        scan of the file tail. ``append`` calls this under the flock on
        every publish: compaction is best-effort, so the WAL can grow
        without bound when snapshots keep failing, and reading the whole
        file there would make each commit O(total WAL)."""
        try:
            size = os.path.getsize(self.wal_path)
        except OSError:
            return None
        buf = b""
        with open(self.wal_path, "rb") as f:
            pos = size
            while pos > 0:
                step = min(65536, pos)
                pos -= step
                f.seek(pos)
                buf = f.read(step) + buf
                lines = buf.decode("utf-8", errors="replace").split("\n")
                # lines[-1] is a torn tail (or empty past the final
                # newline); lines[0] may be a mid-line fragment unless
                # the scan reached the start of the file.
                first = 0 if pos == 0 else 1
                for line in reversed(lines[first:-1]):
                    if not line.strip():
                        continue
                    entry = self._parse_line(line)
                    if entry is not None:
                        return entry
        return None

    def _read_wal(self, count: bool = True) -> Tuple[List[dict], int]:
        """Parse the WAL; returns ``(entries, torn_skipped)``. A partial
        final line (torn append) is skipped; an unparsable line anywhere
        else is also skipped (a sealed torn record from a retried append
        sits mid-file) — whether the log is still usable past it is
        decided by :meth:`load`'s chain validation, which stops at any
        real gap."""
        if not os.path.exists(self.wal_path):
            return [], 0
        with open(self.wal_path) as f:
            raw = f.read()
        entries: List[dict] = []
        torn = 0
        lines = raw.split("\n")
        complete = lines[:-1]  # text after the final newline is a torn tail
        if lines[-1]:
            torn += 1
        for i, line in enumerate(complete):
            if not line.strip():
                continue
            entry = self._parse_line(line)
            if entry is None:
                if i == len(complete) - 1:
                    torn += 1  # torn mid-record on the last complete line
                elif count:
                    BUS.count("stream.log.corrupt_line")
                continue
            entries.append(entry)
        if torn and count:
            BUS.count("stream.log.torn_skipped", torn)
        return entries, torn

    def load_snapshot(self) -> Tuple[Optional[dict], List[Tuple[str, str]]]:
        """Newest loadable snapshot generation (primary, else ``.bak``);
        returns ``(state_or_None, notes)`` in the checkpoint-recovery
        shape (why each skipped candidate was rejected)."""
        notes: List[Tuple[str, str]] = []
        for candidate in (self.snap_path, self.snap_path + ".bak"):
            if not os.path.exists(candidate):
                notes.append((candidate, "missing"))
                continue
            try:
                with np.load(candidate) as data:
                    state = {
                        "num_nodes": int(data["num_nodes"]),
                        "u": np.asarray(data["u"]),
                        "v": np.asarray(data["v"]),
                        "w": np.asarray(data["w"]),
                        "in_tree": np.asarray(data["in_tree"], dtype=bool),
                        "seq": int(data["seq"]),
                        "digest": str(data["digest"]),
                        "notifications": (
                            json.loads(str(data["notifications"]))
                            if "notifications" in data.files else []
                        ),
                    }
            except Exception as e:  # torn/corrupt: fall to the next generation
                notes.append((candidate, f"{type(e).__name__}: {e}"))
                continue
            if candidate.endswith(".bak"):
                BUS.count("stream.log.snap_fallback")
            return state, notes
        return None, notes

    def load(self) -> Tuple[Optional[dict], List[dict], List[Tuple[str, str]]]:
        """Replay input: ``(snapshot_state_or_None, chained_entries, notes)``.

        ``chained_entries`` are the WAL windows that verifiably follow the
        snapshot: contiguous sequence numbers starting at ``seq + 1`` whose
        ``prev`` digests chain from the snapshot digest. The first entry
        breaking the chain stops the list (``stream.log.chain_broken``) —
        the snapshot/log-disagreement degraded path.
        """
        state, notes = self.load_snapshot()
        entries, _torn = self._read_wal()
        if state is None:
            return None, [], notes
        chained: List[dict] = []
        seq, head = state["seq"], state["digest"]
        broken = False
        for entry in entries:
            if entry["seq"] <= seq:
                continue  # covered by the snapshot (compaction raced a crash)
            if entry["seq"] != seq + 1 or entry["prev"] != head:
                BUS.count("stream.log.chain_broken")
                notes.append((
                    self.wal_path,
                    f"chain break at seq {entry['seq']} "
                    f"(expected {seq + 1} following {head[:12]}...)",
                ))
                broken = True
                break
            chained.append(entry)
            seq, head = entry["seq"], entry["digest"]
        if broken:
            self._truncate_to_chain()
        return state, chained, notes

    def _truncate_to_chain(self) -> None:
        """Repair a mid-log chain break: rewrite the WAL down to the
        prefix that chains from the snapshot. Entries past the break are
        unreachable by replay, but ``append`` validates against the LAST
        parsable line — leaving them in place refuses every publish from
        the recovered head forever (the client adopts the dead tail
        digest, the session keeps recovering to the chained head: a
        re-sync livelock). The chain is re-derived from the freshest
        snapshot generation INSIDE the flock, so a concurrent writer that
        just advanced the snapshot (making the tail chain again) is never
        clobbered. Best-effort like compaction: a failed rewrite leaves
        the pre-repair state."""
        try:
            with _flocked(self.wal_path):
                state, _notes = self.load_snapshot()
                if state is None:
                    return
                entries, _torn = self._read_wal(count=False)
                keep: List[dict] = []
                seq, head = state["seq"], state["digest"]
                for entry in entries:
                    if entry["seq"] <= seq:
                        continue  # covered: compaction's job either way
                    if entry["seq"] != seq + 1 or entry["prev"] != head:
                        break
                    keep.append(entry)
                    seq, head = entry["seq"], entry["digest"]
                if len(keep) == len(entries):
                    return
                tmp = self.wal_path + ".tmp"
                with open(tmp, "w") as f:
                    for e in keep:
                        f.write(
                            json.dumps({"schema": WAL_SCHEMA, **e}) + "\n"
                        )
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.wal_path)
            BUS.count("stream.log.chain_truncated")
        except (OSError, TimeoutError):
            pass
