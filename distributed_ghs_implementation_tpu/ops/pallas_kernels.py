"""Fused Pallas TPU kernels for the per-level inner loop.

The per-level hot path is a chain of XLA-scheduled gather / select /
``segment_min`` / pointer-jump ops with every intermediate materialized in
HBM. ``tools/test_pallas_gather.py`` measured the dominant cost — the
fragment-id random gather (~480 ms at RMAT-20) — dropping ~7x when the
fragment table is VMEM-resident inside a Pallas kernel. This module turns
that probe into production kernels:

* :func:`fused_ell_row_min` — the ELL kernel's per-bucket MOE search
  (``models.boruvka._ell_level``): the two fragment gathers
  (``fragment[verts]``, ``fragment[dstb]``), the outgoing-edge mask, and
  the rank-keyed row minimum run in ONE pass over VMEM-blocked edge
  buckets, with the fragment table resident in VMEM across the whole
  grid. Subsumes the reduction half of ``ops.segment_ops.fragment_moe``
  in the degree-bucketed layout.
* :func:`fused_gather_key` — the flat kernels' MOE front half
  (``fragment_moe`` with a non-identity partition): fragment gathers for
  both endpoints plus the alive-mask rank select in one VMEM pass; the
  n-segment ``segment_min`` scatter stays in XLA (a dense-reduction
  segment scatter has no efficient Pallas form — the ELL layout is the
  fused answer to that op).
* :func:`fused_hook_compress` — ``ops.union_find.break_symmetric_hooks``
  + bounded ``pointer_jump`` + the final relabel gather fused into one
  kernel: the parent array stays in VMEM across every jump, so no
  intermediate parent array ever round-trips HBM. ``ceil(log2 n)`` jumps
  reach the fixpoint of any hook forest (each jump doubles pointer
  reach), so the bounded loop is exact, not approximate.

Selection (the speculative/fallback discipline of the round-5 fused
filter+compaction work):

* ``kernel="pallas" | "xla"`` threads through ``models/boruvka.py``,
  ``batch/lanes.py``, and ``parallel/rank_sharded.py`` /
  ``parallel/lane.py`` as a STATIC trace-time argument — both variants
  compile side by side and cache independently.
* :func:`kernel_choice` resolves a per-solve override, then the process
  default (:func:`set_default_kernel`, the ``serve --kernel`` flag), then
  the ``GHS_KERNEL`` env var, then ``auto``: Pallas on TPU backends where
  the import-time capability probe passes, XLA everywhere else. On
  non-TPU backends Pallas kernels run in interpret mode (lowered to
  plain XLA ops) — bit-exact, so CPU CI asserts kernel parity without
  hardware; ``auto`` never picks the interpreted path for throughput.
* A runtime Pallas failure trips :func:`disable_pallas` — a sticky
  process-wide fallback to XLA (``kernel.fallback`` on the obs bus) so
  one Mosaic regression degrades throughput, never availability.

Every wrapper also has a shape guard (``*_shape_ok``): geometries past
the VMEM budget (fragment table > ``KernelGeometry.table_max_elems``,
hook arrays > ``KernelGeometry.hook_max_nodes``) or off the tiling grid
route back to the XLA form at trace time, so ``kernel="pallas"`` is
always safe to request.

The block/budget numbers live in :class:`KernelGeometry` — an immutable,
validated knob surface the offline autotuner (``tune/space.py``) searches
over. The module default is the hand-derived geometry the kernels shipped
with; :func:`set_geometry` / :func:`geometry_scope` override it
process-wide (what ``tune/measure.py`` uses to compile each candidate and
what installing a TuningRecord with a Pallas winner applies).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import math
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ghs_implementation_tpu.obs.events import BUS

INT32_MAX = np.iinfo(np.int32).max

#: VPU lane width — flat e-sized arrays reshape to ``(rows, 128)``. A
#: hardware fact, not a tunable: every geometry is expressed in 128-lane
#: rows.
_LANES = 128


@dataclasses.dataclass(frozen=True)
class KernelGeometry:
    """The tunable VMEM/tiling knobs of the fused kernels.

    Defaults are the hand-derived shipping geometry; the autotuner
    (``tune/``) searches the validated neighborhood. Every field is a
    power of two — block sizes must divide the padded (power-of-two)
    row counts exactly because Pallas grids have no remainder step —
    and is capped at a hard VMEM ceiling so no candidate can even be
    *constructed* past the budget.

    * ``table_max_elems`` — fragment-table ceiling for table-resident
      kernels: the whole table sits in VMEM beside the streamed blocks
      (1M int32 = 4 MB of ~16 MB at the default).
    * ``hook_max_nodes`` — hook+compress ceiling: the kernel holds the
      parent array plus take temporaries in VMEM for every jump
      (2^19 int32 = 2 MB per buffer at the default).
    * ``ell_block_elems`` — elements per streamed ELL block
      (rows x width).
    * ``flat_block_rows`` — row cap per streamed flat block (rows of
      ``_LANES`` lanes).
    """

    table_max_elems: int = 1 << 20
    hook_max_nodes: int = 1 << 19
    ell_block_elems: int = 1 << 15
    flat_block_rows: int = 256

    #: Hard ceilings (class-level, not fields): int32 elems that still fit
    #: a ~16 MB VMEM beside the streamed blocks / loop temporaries.
    _CEILINGS = {
        "table_max_elems": 1 << 22,
        "hook_max_nodes": 1 << 20,
        "ell_block_elems": 1 << 18,
        "flat_block_rows": 1 << 12,
    }

    def __post_init__(self):
        for name, ceiling in self._CEILINGS.items():
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ValueError(
                    f"KernelGeometry.{name} must be a positive int, got {v!r}"
                )
            if v & (v - 1):
                raise ValueError(
                    f"KernelGeometry.{name} must be a power of two "
                    f"(Pallas grids have no remainder step), got {v}"
                )
            if v > ceiling:
                raise ValueError(
                    f"KernelGeometry.{name}={v} exceeds the VMEM ceiling "
                    f"{ceiling}"
                )

    def to_json(self) -> dict:
        return {
            "table_max_elems": self.table_max_elems,
            "hook_max_nodes": self.hook_max_nodes,
            "ell_block_elems": self.ell_block_elems,
            "flat_block_rows": self.flat_block_rows,
        }

    @classmethod
    def from_json(cls, data: dict) -> "KernelGeometry":
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - fields
        if unknown:
            raise ValueError(
                f"unknown KernelGeometry field(s) {sorted(unknown)}"
            )
        return cls(**{k: int(v) for k, v in data.items()})


DEFAULT_GEOMETRY = KernelGeometry()
_GEOMETRY: KernelGeometry = DEFAULT_GEOMETRY


def geometry() -> KernelGeometry:
    """The process's active kernel geometry (trace-time reads)."""
    return _GEOMETRY


def set_geometry(geom: KernelGeometry | None) -> None:
    """Override the process geometry (``None`` restores the default).
    Takes effect at the next trace — already-compiled executables keep
    the geometry they compiled with (it is baked into the program)."""
    global _GEOMETRY
    if geom is not None and not isinstance(geom, KernelGeometry):
        raise TypeError(f"expected KernelGeometry or None, got {type(geom)}")
    _GEOMETRY = DEFAULT_GEOMETRY if geom is None else geom


@contextlib.contextmanager
def geometry_scope(geom: KernelGeometry):
    """Trace candidate kernels under a temporary geometry (the autotuner's
    measurement scope); restores the previous geometry on exit."""
    global _GEOMETRY
    prev = _GEOMETRY
    set_geometry(geom)
    try:
        yield geom
    finally:
        _GEOMETRY = prev


VALID_KERNELS = ("auto", "pallas", "xla")

_LOCK = threading.Lock()
_DEFAULT_KERNEL: str | None = None  # set_default_kernel (serve --kernel)
_DISABLED_REASON: str | None = None  # sticky runtime fallback
_PROBE_RESULT: bool | None = None
_PROBE_ERROR: str | None = None
# Measured per-bucket winners from an installed TuningRecord
# (tune/record.py install_record): (n_pad, m_pad, lanes, mode) -> kernel.
_TUNED_KERNELS: dict | None = None
_TUNED_SOURCE: dict | None = None  # {"fingerprint", "path", "entries"}


def _interpret() -> bool:
    """Interpret mode off-TPU: kernels lower to plain XLA ops — bit-exact
    and compilable anywhere, which is what lets CPU CI assert parity."""
    return jax.default_backend() != "tpu"


def _probe() -> bool:
    """One-shot capability probe: build and run the probe gather kernel on
    the current backend (compiled on TPU, interpreted elsewhere)."""
    global _PROBE_RESULT, _PROBE_ERROR
    with _LOCK:
        if _PROBE_RESULT is not None:
            return _PROBE_RESULT
    try:
        from jax.experimental import pallas as pl

        def gather_kernel(table_ref, idx_ref, out_ref):
            out_ref[...] = jnp.take(table_ref[...], idx_ref[...], axis=0)

        table = jnp.arange(256, dtype=jnp.int32)
        idx = jnp.full((2, _LANES), 3, jnp.int32)
        out = pl.pallas_call(
            gather_kernel,
            grid=(1,),
            in_specs=[
                pl.BlockSpec(table.shape, lambda i: (0,)),
                pl.BlockSpec(idx.shape, lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec(idx.shape, lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct(idx.shape, table.dtype),
            interpret=_interpret(),
        )(table, idx)
        ok = bool(jax.device_get(out)[0, 0] == 3)
        err = None if ok else "probe kernel returned wrong values"
    except Exception as ex:  # noqa: BLE001 — any failure means unavailable
        ok, err = False, f"{type(ex).__name__}: {ex}"
    with _LOCK:
        _PROBE_RESULT, _PROBE_ERROR = ok, err
    return ok


def pallas_supported() -> bool:
    """Can ``kernel="pallas"`` run at all on this process's backend?
    (Compiled on TPU; interpret-mode — exact but slow — elsewhere.)"""
    return _DISABLED_REASON is None and _probe()


def set_default_kernel(choice: str | None) -> None:
    """Set the process-default kernel (the ``serve --kernel`` flag); wins
    over ``GHS_KERNEL``, loses to a per-solve override."""
    global _DEFAULT_KERNEL
    if choice is not None and choice not in VALID_KERNELS:
        raise ValueError(
            f"unknown kernel {choice!r}; expected one of {VALID_KERNELS}"
        )
    _DEFAULT_KERNEL = None if choice in (None, "auto") else choice


def disable_pallas(reason: str) -> None:
    """Sticky process-wide fallback: every later :func:`kernel_choice`
    resolves ``xla`` (``kernel.fallback`` counts the trip)."""
    global _DISABLED_REASON
    with _LOCK:
        already = _DISABLED_REASON is not None
        _DISABLED_REASON = _DISABLED_REASON or reason
    if not already:
        BUS.count("kernel.fallback")


def set_tuned_kernels(
    mapping: dict | None, source: dict | None = None
) -> None:
    """Install measured per-bucket winners (``tune/record.py``'s
    ``install_record`` is the one caller). ``mapping`` maps solver buckets
    ``(n_pad, m_pad, lanes, mode)`` to ``"pallas" | "xla"``; ``None``
    uninstalls. ``source`` is a small provenance dict surfaced by
    :func:`tuned_summary` / :func:`kernel_report` (and the fleet hello's
    ``caps["tuned"]``)."""
    global _TUNED_KERNELS, _TUNED_SOURCE
    if mapping is not None:
        for bucket, win in mapping.items():
            if win not in ("pallas", "xla"):
                raise ValueError(
                    f"tuned winner for bucket {bucket!r} must be "
                    f"pallas|xla, got {win!r}"
                )
    with _LOCK:
        _TUNED_KERNELS = dict(mapping) if mapping is not None else None
        _TUNED_SOURCE = dict(source) if source is not None else None


def tuned_summary() -> dict | None:
    """Provenance of the installed TuningRecord (``None`` when the process
    runs on the probe heuristic alone)."""
    with _LOCK:
        if _TUNED_KERNELS is None:
            return None
        out = dict(_TUNED_SOURCE or {})
        out.setdefault("entries", len(_TUNED_KERNELS))
        return out


def kernel_choice(
    override: str | None = None, *, bucket: tuple | None = None
) -> str:
    """Resolve the effective kernel: per-solve override > process default
    (``set_default_kernel``) > ``GHS_KERNEL`` env > measured auto (an
    installed TuningRecord's winner for ``bucket``) > probe auto (Pallas
    on TPU when the probe passes, XLA everywhere else). Requests for an
    unavailable Pallas degrade to ``"xla"`` — never an error.

    ``bucket`` is the solver bucket ``(n_pad, m_pad, lanes, mode)`` being
    resolved; per-bucket call sites (``batch/lanes``, the sharded lane,
    warmup) pass it so ``auto`` can consult the measured winners. The
    sticky :func:`disable_pallas` fallback outranks a measured Pallas
    winner — a record is a measurement, not an availability proof."""
    request = override or _DEFAULT_KERNEL or os.environ.get("GHS_KERNEL") or "auto"
    if request not in VALID_KERNELS:
        raise ValueError(
            f"unknown kernel {request!r}; expected one of {VALID_KERNELS}"
        )
    if request == "xla":
        return "xla"
    if _DISABLED_REASON is not None:
        return "xla"
    if request == "pallas":
        return "pallas" if pallas_supported() else "xla"
    # auto, measured tier: a TuningRecord for THIS machine pins the
    # bucket's winner (kernel.selected.measured proves selections are
    # measurements, not guesses).
    if bucket is not None and _TUNED_KERNELS:
        win = _TUNED_KERNELS.get(tuple(bucket))
        if win is not None:
            if win == "pallas" and not pallas_supported():
                return "xla"
            BUS.count("kernel.selected.measured")
            return win
    # auto, probe tier: only pick Pallas where it runs compiled —
    # interpret mode is a parity tool, not a throughput path.
    if jax.default_backend() == "tpu" and pallas_supported():
        return "pallas"
    return "xla"


def kernel_report() -> dict:
    """Selection state for drills/stats: what auto resolves to and why."""
    return {
        "backend": jax.default_backend(),
        "supported": pallas_supported(),
        "interpret": _interpret(),
        "default": _DEFAULT_KERNEL or os.environ.get("GHS_KERNEL") or "auto",
        "resolved": kernel_choice(),
        "disabled_reason": _DISABLED_REASON,
        "probe_error": _PROBE_ERROR,
        "tuned": tuned_summary(),
        "geometry": _GEOMETRY.to_json(),
    }


def _reset_for_tests() -> None:
    """Clear sticky selection state (tests simulate a process restart)."""
    global _DEFAULT_KERNEL, _DISABLED_REASON, _PROBE_RESULT, _PROBE_ERROR
    global _TUNED_KERNELS, _TUNED_SOURCE, _GEOMETRY
    with _LOCK:
        _DEFAULT_KERNEL = None
        _DISABLED_REASON = None
        _PROBE_RESULT = None
        _PROBE_ERROR = None
        _TUNED_KERNELS = None
        _TUNED_SOURCE = None
        _GEOMETRY = DEFAULT_GEOMETRY


# ---------------------------------------------------------------------------
# Shape guards — resolved at trace time (shapes are static), so a guarded
# geometry silently takes the XLA form instead of failing.
# ---------------------------------------------------------------------------
def _pow2_factor(x: int, cap: int) -> int:
    """Largest power of two dividing ``x``, capped (block sizes must divide
    the padded row count exactly — Pallas grids have no remainder step).
    The cap is rounded DOWN to a power of two first: a non-pow2 cap would
    otherwise win the ``min`` with a non-divisor and leave the grid's tail
    rows unwritten."""
    if x <= 0:
        return 1
    cap_pow2 = 1 << (max(1, cap).bit_length() - 1)
    return min(cap_pow2, x & (-x))


def ell_shape_ok(
    num_nodes: int, rows: int, width: int,
    geom: KernelGeometry | None = None,
) -> bool:
    g = geom if geom is not None else _GEOMETRY
    return 0 < num_nodes <= g.table_max_elems and rows > 0 and width > 0


def flat_shape_ok(
    num_nodes: int, num_slots: int, geom: KernelGeometry | None = None
) -> bool:
    g = geom if geom is not None else _GEOMETRY
    return (
        0 < num_nodes <= g.table_max_elems
        and num_slots >= _LANES
        and num_slots % _LANES == 0
    )


def hook_shape_ok(
    num_nodes: int, geom: KernelGeometry | None = None
) -> bool:
    g = geom if geom is not None else _GEOMETRY
    return 0 < num_nodes <= g.hook_max_nodes


# ---------------------------------------------------------------------------
# Kernel bodies
# ---------------------------------------------------------------------------
def _ell_row_min_kernel(frag_ref, verts_ref, dst_ref, rank_ref, out_ref):
    """One ELL block: fragment gathers + alive mask + rank-keyed row min,
    fragment table VMEM-resident."""
    frag = frag_ref[...]
    fv = jnp.take(frag, verts_ref[...], axis=0)
    fd = jnp.take(frag, dst_ref[...], axis=0)
    key = jnp.where(fd != fv[:, None], rank_ref[...], INT32_MAX)
    out_ref[...] = jnp.min(key, axis=1)


def _gather_key_kernel(frag_ref, src_ref, dst_ref, rank_ref, fsrc_ref, key_ref):
    """One flat block: both endpoint fragment gathers + the alive-mask rank
    select, one pass (the MOE front half; segment_min stays in XLA)."""
    frag = frag_ref[...]
    fs = jnp.take(frag, src_ref[...], axis=0)
    fd = jnp.take(frag, dst_ref[...], axis=0)
    fsrc_ref[...] = fs
    key_ref[...] = jnp.where(fs != fd, rank_ref[...], INT32_MAX)


def _hook_compress_kernel(parent0_ref, frag_ref, newf_ref, parent_ref, *, num_iters):
    """Symmetric-hook break + ``num_iters`` pointer jumps + the final
    vertex relabel, parent resident in VMEM across every jump."""
    p = parent0_ref[...]
    rows, lanes = p.shape
    row = jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1)
    ids = row * lanes + col
    # break_symmetric_hooks: mutual pair f <-> g, smaller id self-roots.
    pp = jnp.take(p.reshape(-1), p, axis=0)
    p = jnp.where((pp == ids) & (ids < p), ids, p)

    def jump(_, q):
        return jnp.take(q.reshape(-1), q, axis=0)

    p = jax.lax.fori_loop(0, num_iters, jump, p)
    parent_ref[...] = p
    newf_ref[...] = jnp.take(p.reshape(-1), frag_ref[...], axis=0)


# ---------------------------------------------------------------------------
# Wrappers (trace-time entry points; callers guard with *_shape_ok)
# ---------------------------------------------------------------------------
def fused_ell_row_min(fragment, verts, dstb, rankb):
    """Per-row masked rank minimum over one ELL bucket — the fused form of
    ``fragment[verts]`` / ``fragment[dstb]`` / mask / ``min(axis=1)``.
    Pad rows (vertex 0, all-sentinel ranks) come out as INT32_MAX, inert
    under the caller's scatter-min, exactly like the XLA form."""
    from jax.experimental import pallas as pl

    rows, width = dstb.shape
    block = _pow2_factor(
        rows, max(1, _GEOMETRY.ell_block_elems // max(1, width))
    )
    grid = (rows // block,)
    return pl.pallas_call(
        _ell_row_min_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(fragment.shape, lambda i: (0,)),
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block, width), lambda i: (i, 0)),
            pl.BlockSpec((block, width), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((rows,), jnp.int32),
        interpret=_interpret(),
    )(fragment, verts, dstb, rankb)


def fused_gather_key(fragment, src, dst, rank):
    """``(fragment[src], masked rank key)`` in one VMEM pass over the flat
    slot arrays (the non-identity ``fragment_moe`` front half)."""
    from jax.experimental import pallas as pl

    e = src.shape[0]
    rows = e // _LANES
    block = _pow2_factor(rows, _GEOMETRY.flat_block_rows)
    shape2 = (rows, _LANES)
    blk = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
    fsrc, key = pl.pallas_call(
        _gather_key_kernel,
        grid=(rows // block,),
        in_specs=[pl.BlockSpec(fragment.shape, lambda i: (0,)), blk, blk, blk],
        out_specs=(blk, blk),
        out_shape=(
            jax.ShapeDtypeStruct(shape2, jnp.int32),
            jax.ShapeDtypeStruct(shape2, jnp.int32),
        ),
        interpret=_interpret(),
    )(fragment, src.reshape(shape2), dst.reshape(shape2), rank.reshape(shape2))
    return fsrc.reshape(-1), key.reshape(-1)


def fused_hook_compress(has_moe, moe_dst_frag, fragment):
    """One merge round fused: hook, symmetric break, bounded pointer jump,
    vertex relabel — same contract as ``union_find.hook_and_compress``
    (``(new_fragment, parent_star)``), intermediates VMEM-only.

    Exactness: ``ceil(log2 n)`` jumps double pointer reach past any chain
    a forest of n nodes can hold, so the bounded loop lands on the same
    fixpoint the XLA ``while_loop`` early-exits at.
    """
    from jax.experimental import pallas as pl

    n = fragment.shape[0]
    pad = (-n) % _LANES
    total = n + pad
    ids = jnp.arange(total, dtype=jnp.int32)
    if pad:
        # Pad entries are isolated self-roots: no real entry can point at
        # them (parent values are node ids < n), so they perturb nothing.
        has_moe = jnp.concatenate([has_moe, jnp.zeros(pad, bool)])
        moe_dst_frag = jnp.concatenate([moe_dst_frag, ids[n:]])
        fragment = jnp.concatenate([fragment, ids[n:]])
    parent0 = jnp.where(has_moe, moe_dst_frag, ids)
    rows = total // _LANES
    shape2 = (rows, _LANES)
    num_iters = max(1, math.ceil(math.log2(max(2, total))))
    spec = pl.BlockSpec(shape2, lambda: (0, 0))
    newf, parent = pl.pallas_call(
        functools.partial(_hook_compress_kernel, num_iters=num_iters),
        in_specs=[spec, spec],
        out_specs=(spec, spec),
        out_shape=(
            jax.ShapeDtypeStruct(shape2, jnp.int32),
            jax.ShapeDtypeStruct(shape2, jnp.int32),
        ),
        interpret=_interpret(),
    )(parent0.reshape(shape2), fragment.reshape(shape2))
    return newf.reshape(-1)[:n], parent.reshape(-1)[:n]
