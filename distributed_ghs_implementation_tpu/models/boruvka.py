"""Batched Borůvka/GHS MST solver — the flagship model.

The whole GHS protocol (``/root/reference/ghs_implementation.py:118-413``)
runs here as one on-device loop. One *level* (the reference's round shape,
SURVEY.md §3.4) is:

  1. candidate filter — intra-fragment edges die (TEST -> REJECT analog),
  2. ``fragment_moe`` — per-fragment minimum outgoing edge via two segment
     minima (TEST/ACCEPT + REPORT convergecast analog),
  3. ``hook_and_compress`` — symmetric-hook resolution + pointer jumping
     (CONNECT/INITIATE/CHANGEROOT analog),
  4. chosen slots are recorded as MST edges (BRANCH marking analog,
     ``ghs_implementation.py:130-131``).

Levels iterate in a ``lax.while_loop`` until no fragment has an outgoing edge
— the multi-component-safe analog of root termination on ``best_weight ==
inf`` (``ghs_implementation.py:316-320``). At most ``ceil(log2 n)`` levels run
because every active fragment merges each level. Unlike the reference's
thread/MPI races (wrong MSTs at 20+ vertices, SURVEY.md preamble), every step
is deterministic: same graph in, identical MST out.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ghs_implementation_tpu.graphs.edgelist import Graph
from distributed_ghs_implementation_tpu.ops.segment_ops import INT32_MAX, fragment_moe
from distributed_ghs_implementation_tpu.ops.union_find import hook_and_compress


class BoruvkaState(NamedTuple):
    """Carried through the level loop (the analog of all per-node protocol
    state — ``NodeState``/``level``/``fragment_id``/``best_edge`` at
    ``ghs_implementation.py:55-66`` — flattened into three arrays)."""

    fragment: jax.Array  # [n] int32: fragment (root) id per vertex
    mst_slots: jax.Array  # [e2] bool: directed slots chosen as MST edges
    level: jax.Array  # scalar int32: levels completed
    progress: jax.Array  # scalar bool: did the last level merge anything


def boruvka_level(
    state: BoruvkaState,
    src: jax.Array,
    dst: jax.Array,
    w: jax.Array,
    *,
    axis_name: str | None = None,
) -> BoruvkaState:
    """One GHS/Borůvka level over (optionally sharded) directed edge slots."""
    fragment = state.fragment
    has_moe, _, moe_slot, moe_dst_frag = fragment_moe(
        fragment, src, dst, w, axis_name=axis_name
    )
    new_fragment = hook_and_compress(has_moe, moe_dst_frag, fragment)

    # Record chosen slots. Sharded: each shard owns a contiguous global slot
    # range and marks only winners that fall inside it.
    e = src.shape[0]
    if axis_name is None:
        safe = jnp.where(has_moe, moe_slot, 0)
        mst_slots = state.mst_slots.at[safe].max(has_moe)
    else:
        shard = jax.lax.axis_index(axis_name).astype(jnp.int32)
        local = moe_slot - shard * e
        mine = has_moe & (local >= 0) & (local < e)
        safe = jnp.where(mine, local, 0)
        mst_slots = state.mst_slots.at[safe].max(mine)

    return BoruvkaState(
        fragment=new_fragment,
        mst_slots=mst_slots,
        level=state.level + 1,
        progress=jnp.any(has_moe),
    )


def _max_levels(num_nodes: int) -> int:
    return max(1, math.ceil(math.log2(max(num_nodes, 2)))) + 1


def boruvka_solve(
    fragment0: jax.Array,
    src: jax.Array,
    dst: jax.Array,
    w: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full single-device solve: ``(mst_slots[e2], fragment[n], levels)``.

    Jit-friendly: fixed shapes, on-device ``while_loop``, no host sync inside.
    """
    n = fragment0.shape[0]
    e2 = src.shape[0]
    state = BoruvkaState(
        fragment=fragment0,
        mst_slots=jnp.zeros(e2, dtype=bool),
        level=jnp.zeros((), jnp.int32),
        progress=jnp.ones((), bool),
    )
    max_levels = _max_levels(n)

    def cond(s: BoruvkaState):
        return s.progress & (s.level < max_levels)

    def body(s: BoruvkaState):
        return boruvka_level(s, src, dst, w)

    final = jax.lax.while_loop(cond, body, state)
    return final.mst_slots, final.fragment, final.level


@functools.lru_cache(maxsize=32)
def make_solver(num_nodes: int, num_slots: int, weight_dtype: str):
    """Compiled solver for a given shape; cached across same-shape graphs."""
    del num_nodes, num_slots, weight_dtype  # cache key only; shapes come from args
    return jax.jit(boruvka_solve)


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


def solve_graph(graph: Graph, *, bucket_shapes: bool = True) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host entry: run the solver on a ``Graph``.

    Returns ``(mst_edge_ids, fragment, levels)`` where ``mst_edge_ids`` are
    indices into ``graph.u/v/w`` (undirected), sorted ascending.

    ``bucket_shapes`` pads edge slots and the vertex array to powers of two so
    graphs in the same size bucket share one compiled kernel (padding vertices
    are isolated self-fragments; padding slots are inert self-edges).
    """
    n = graph.num_nodes
    if n == 0 or graph.num_edges == 0:
        return np.zeros(0, dtype=np.int64), np.arange(n, dtype=np.int32), 0
    n_pad = _next_pow2(n) if bucket_shapes else n
    e_pad = _next_pow2(2 * graph.num_edges) if bucket_shapes else None
    src_np, dst_np, w_np = graph.directed_arrays(pad_to=e_pad)
    solver = make_solver(n_pad, src_np.shape[0], str(w_np.dtype))
    fragment0 = jnp.arange(n_pad, dtype=jnp.int32)
    mst_slots, fragment, levels = solver(
        fragment0, jnp.asarray(src_np), jnp.asarray(dst_np), jnp.asarray(w_np)
    )
    slots = np.nonzero(np.asarray(mst_slots))[0]
    edge_ids = np.unique(slots >> 1)
    return edge_ids, np.asarray(fragment)[:n], int(levels)
