"""TPU-native distributed minimum-spanning-tree framework.

A brand-new framework with the capabilities of the reference GHS implementation
(``Trisanu-007/Distributed_GHS_Implementation``): exact MSTs of weighted graphs,
NetworkX weight parity, graph generation/partitioning tooling, experiment
harness, and visualization — redesigned TPU-first.

Instead of the reference's per-vertex message passing (one thread or MPI rank
per graph vertex, ``/root/reference/ghs_implementation.py:46-116`` and
``ghs_implementation_mpi.py:40-115``), the GHS protocol is recast as a batched
Borůvka-style graph-contraction kernel: the TEST/ACCEPT/REJECT minimum-outgoing-
edge search becomes a ``segment_min`` over an edge list, the CONNECT/INITIATE/
CHANGEROOT fragment merge becomes pointer-jumping union-find, and levels run in
an on-device ``lax.while_loop``, with edges shardable over a TPU mesh and
per-level minima combined over ICI.

Public API (mirrors the reference surface, ``ghs_implementation.py:416-442``):

    >>> from distributed_ghs_implementation_tpu import GHSAlgorithm
    >>> mst = GHSAlgorithm(num_nodes, edges).run()

or the functional form:

    >>> from distributed_ghs_implementation_tpu import minimum_spanning_tree
"""

from distributed_ghs_implementation_tpu.api import (
    GHSAlgorithm,
    MSTResult,
    minimum_spanning_forest,
    minimum_spanning_tree,
)
from distributed_ghs_implementation_tpu.graphs.edgelist import Graph

__version__ = "0.1.0"

__all__ = [
    "GHSAlgorithm",
    "Graph",
    "MSTResult",
    "minimum_spanning_forest",
    "minimum_spanning_tree",
    "__version__",
]
