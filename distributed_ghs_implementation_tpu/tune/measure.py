"""The seeded offline search: score candidates, pick measured winners.

Per bucket the search enumerates the valid candidates (``tune/space.py``)
and, for each, compiles a *fresh, uncached* executable under that
candidate's geometry (``pallas_kernels.geometry_scope`` — the shared
lane-solver cache is deliberately bypassed: its key has no geometry
dimension, and the search must never poison a serving cache or reuse a
different candidate's program). Scoring follows the bench conventions:
one warm call (pays compile + first dispatch), then the median of
``repeats`` timed calls on a seeded per-bucket workload.

Trust discipline:

* **Parity before trust** — a Pallas candidate's outputs are compared
  element-exactly against the bucket's XLA reference before its timing
  can win (off-TPU this is the interpret-mode parity check CPU CI runs).
  A mismatch scores the candidate dead (``tune.search.rejected``).
* **Failure carve-outs** — any exception while compiling or running a
  candidate (a Mosaic lowering error, a geometry ValueError, an OOM)
  marks that candidate dead and the search continues; the search itself
  never trips the process's sticky ``disable_pallas`` fallback and never
  crashes on a bad candidate.
* **CPU pin** — off TPU, Pallas runs in interpret mode, which is a
  correctness tool, not a throughput path: every Pallas candidate scores
  as fallback and the winner deterministically pins ``xla`` (``source:
  "cpu-pin"``). With ``dry=True`` the same pin applies on any backend
  (``"dry-pin"``) and timing is skipped entirely — two dry runs produce
  identical records byte for byte, which CI's ``gate-tune-v1`` asserts.

Mesh buckets (``mode="mesh"``) score on the per-device flat proxy: the
rank-sharded programs call the same fused kernels shard-locally at the
per-device shapes, so the proxy measures the kernels the mesh actually
runs, without needing a device mesh inside the tuner.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from distributed_ghs_implementation_tpu.batch import lanes as lanes_mod
from distributed_ghs_implementation_tpu.graphs.generators import (
    gnm_random_graph,
)
from distributed_ghs_implementation_tpu.models.boruvka import _solve_from_iota
from distributed_ghs_implementation_tpu.obs.events import BUS
from distributed_ghs_implementation_tpu.ops import pallas_kernels as _pk
from distributed_ghs_implementation_tpu.tune import record as record_mod
from distributed_ghs_implementation_tpu.tune import space as space_mod

#: The repo-wide bench seed (bench.py) — the search is a benchmark too.
SEED = 24

Bucket = record_mod.Bucket


def normalize_buckets(buckets: Iterable[Sequence]) -> List[Bucket]:
    """Dedupe + canonicalize a bucket list (sorted, ints, validated)."""
    seen = set()
    for b in buckets:
        n, m, lanes, mode = b
        key = (int(n), int(m), max(0, int(lanes)), str(mode))
        if key[3] not in space_mod.VALID_MODES:
            raise ValueError(
                f"unknown bucket mode {key[3]!r} in tune bucket {b!r}"
            )
        seen.add(key)
    return sorted(seen)


def mesh_bucket(num_nodes: int, num_edges: int, n_dev: int) -> Bucket:
    """The mesh-lane bucket a RAW oversize workload stages at on an
    ``n_dev``-device mesh — mirrors ``ShardedLane.pad_shape`` (bucket
    sizes, rank width rounded up to the 8*n_dev byte-alignment unit)."""
    import math

    from distributed_ghs_implementation_tpu.models.boruvka import _bucket_size

    n_dev = max(1, int(n_dev))
    n_pad = _bucket_size(max(1, num_nodes))
    unit = 8 * n_dev
    m_pad = int(math.ceil(_bucket_size(max(1, num_edges)) / unit) * unit)
    return (n_pad, m_pad, n_dev, "mesh")


def _bucket_seed(seed: int, n_pad: int, m_pad: int) -> int:
    return (seed ^ (n_pad * 1_000_003 + m_pad)) & 0x7FFFFFFF


def _bucket_graph(n_pad: int, m_pad: int, seed: int):
    """A seeded workload graph that pads into exactly this bucket, or
    ``None`` when no simple graph can (next-pow2 inflation past the
    distinct-pair count — such buckets carry no measurable traffic)."""
    n = max(2, n_pad)
    m = min(m_pad, n * (n - 1) // 2)
    if lanes_mod.bucket_of(n, m) != (n_pad, m_pad):
        return None
    return gnm_random_graph(
        n, m, seed=_bucket_seed(seed, n_pad, m_pad), ensure_connected=False
    )


def _lane_runner(graph, n_pad, m_pad, lanes, mode, candidate):
    """A zero-arg callable running one *uncached* lane-solver dispatch
    for the candidate; returns comparable host arrays."""
    stacked = lanes_mod.stack_lanes(
        [graph] * min(lanes, 2), lanes=lanes, mode=mode
    )
    with _pk.geometry_scope(candidate.geometry):
        solver = lanes_mod._compile_bucket(
            n_pad, m_pad, lanes, mode, candidate.kernel
        )

    def run():
        return jax.device_get(solver(*stacked.arrays))

    return run


def _single_runner(graph, n_pad, m_pad, candidate):
    """Uncached single-graph (and mesh per-device proxy) dispatch."""
    src, dst, rank, ra, rb = graph.rank_arrays(
        pad_edges_to=2 * m_pad, pad_ranks_to=m_pad
    )
    with _pk.geometry_scope(candidate.geometry):
        fn = jax.jit(
            functools.partial(
                _solve_from_iota, num_nodes=n_pad, kernel=candidate.kernel
            )
        ).lower(src, dst, rank, ra, rb).compile()

    def run():
        return jax.device_get(fn(src, dst, rank, ra, rb))

    return run


def _make_runner(bucket: Bucket, candidate, graph):
    n_pad, m_pad, lanes, mode = bucket
    if mode in ("fused", "vmap") and lanes >= 1:
        return _lane_runner(graph, n_pad, m_pad, lanes, mode, candidate)
    return _single_runner(graph, n_pad, m_pad, candidate)


def _outputs_equal(a, b) -> bool:
    if a is None or b is None:
        return False
    for x, y in zip(a, b):
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            return False
    return True


def _median(times: List[float]) -> float:
    times = sorted(times)
    return times[len(times) // 2]


def search(
    buckets: Iterable[Sequence],
    *,
    repeats: int = 5,
    warm: int = 1,
    seed: int = SEED,
    dry: bool = False,
) -> dict:
    """Run the offline search over ``buckets``; returns a ``ghs-tuning-v1``
    record dict (``tune/record.py`` persists/installs it).

    ``dry`` skips all timing and pins winners (``xla``) on any backend —
    the deterministic CI mode. Off TPU the pin applies regardless of
    ``dry`` (interpret-mode Pallas never wins on time), so a CPU search
    is always byte-reproducible.
    """
    if repeats < 1:
        raise ValueError(f"repeats must be >= 1, got {repeats}")
    bucket_list = normalize_buckets(buckets)
    on_tpu = jax.default_backend() == "tpu"
    pinned = dry or not on_tpu
    pin_source = "dry-pin" if (dry and on_tpu) else "cpu-pin"
    entries: Dict[Bucket, dict] = {}
    with BUS.span(
        "tune.search", cat="tune",
        buckets=len(bucket_list), dry=dry, pinned=pinned,
    ):
        for bucket in bucket_list:
            entries[bucket] = _search_bucket(
                bucket, repeats=repeats, warm=warm, seed=seed,
                pinned=pinned, pin_source=pin_source,
            )
    return record_mod.new_record(entries, pinned=pinned)


def _search_bucket(
    bucket: Bucket, *, repeats: int, warm: int, seed: int,
    pinned: bool, pin_source: str,
) -> dict:
    n_pad, m_pad, lanes, mode = bucket
    candidates = space_mod.enumerate_candidates(n_pad, m_pad, lanes, mode)
    rejected = space_mod.raw_space_size(mode) - len(candidates)
    for c in candidates:
        BUS.count("tune.search.candidate")
        BUS.instant(
            "tune.search.candidate_detail", cat="tune",
            bucket=record_mod.bucket_key_str(bucket), candidate=c.label(),
        )
    graph = _bucket_graph(n_pad, m_pad, seed)
    if graph is None:
        # Next-pow2 inflation past the distinct-pair count: no simple
        # graph pads here, so there is nothing to measure — the probe
        # heuristic keeps the bucket.
        rejected += len(candidates) - 1
        BUS.count("tune.search.rejected", len(candidates) - 1)
        return {
            "kernel": _pk.kernel_choice(),
            "source": "unreachable",
            "geometry": _pk.DEFAULT_GEOMETRY.to_json(),
            "candidates": len(candidates),
            "rejected": rejected,
            "parity": "skipped",
        }

    reference = None  # the XLA candidate's outputs — the parity oracle
    scores: List[Tuple[float, int]] = []  # (median_s, candidate index)
    parity = "skipped"
    dead = 0
    for idx, cand in enumerate(candidates):
        # Pinned mode never times, and only parity-checks one
        # representative Pallas geometry (the first): off-TPU every
        # Pallas candidate is fallback by construction, so the cheap
        # interpret parity probe is about exercising the oracle, not
        # ranking losers.
        is_parity_rep = cand.kernel == "pallas" and (
            reference is not None and parity == "skipped"
        )
        if pinned and cand.kernel == "pallas" and not is_parity_rep:
            continue
        try:
            run = _make_runner(bucket, cand, graph)
            out = run()
            if cand.kernel == "xla":
                reference = out
            else:
                ok = _outputs_equal(reference, out)
                if parity != "failed":  # a parity failure is sticky
                    parity = "ok" if ok else "failed"
                if not ok:
                    dead += 1
                    BUS.count("tune.search.rejected")
                    BUS.instant(
                        "tune.search.parity_failed", cat="tune",
                        bucket=record_mod.bucket_key_str(bucket),
                        candidate=cand.label(),
                    )
                    continue
            if pinned:
                continue
            for _ in range(max(0, warm - 1)):
                run()
            times = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                run()
                times.append(time.perf_counter() - t0)
            scores.append((_median(times), idx))
        except Exception as ex:  # noqa: BLE001 — scored dead, search lives
            dead += 1
            BUS.count("tune.search.rejected")
            BUS.instant(
                "tune.search.candidate_failed", cat="tune",
                bucket=record_mod.bucket_key_str(bucket),
                candidate=cand.label(), error=f"{type(ex).__name__}: {ex}",
            )

    rejected += dead
    if pinned or not scores:
        winner = candidates[0]  # the XLA reference
        return {
            "kernel": winner.kernel,
            "source": pin_source if pinned else "no-survivors",
            "geometry": winner.geometry.to_json(),
            "candidates": len(candidates),
            "rejected": rejected,
            "parity": parity,
        }
    scores.sort()
    best_s, best_idx = scores[0]
    winner = candidates[best_idx]
    entry = {
        "kernel": winner.kernel,
        "source": "measured",
        "geometry": winner.geometry.to_json(),
        "candidates": len(candidates),
        "rejected": rejected,
        "parity": parity,
        "median_s": round(best_s, 6),
    }
    if len(scores) > 1:
        entry["runner_up_s"] = round(scores[1][0], 6)
    return entry
